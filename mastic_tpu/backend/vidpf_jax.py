"""Batched VIDPF: dense level-synchronous gen / eval over JAX arrays.

Byte-exact twin of the scalar mastic_tpu.vidpf (itself conformance-
locked against /root/reference/test_vec/mastic/), with the per-report
pointer tree replaced by (reports x nodes) arrays:

* one fixed-key AES key schedule per (report, usage), reused for every
  node of that report's tree (see mastic_tpu/backend/xof_jax.py);
* within a level, all nodes extend / correct / convert / hash in one
  fused batch; the level loop is the only sequential axis (it is a PRG
  chain, reference vidpf.py:250-258);
* every secret-dependent choice is a lane select (jnp.where) — the
  constant-time discipline the reference asks for (vidpf.py:116-119)
  holds by construction.

Field payloads are carried as plain (non-Montgomery) 16-bit limbs:
the VIDPF only ever adds/subtracts payloads, so no domain conversion
is needed until the FLP (which multiplies) takes over.
"""

import os
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..common import to_le_bytes
from ..dst import USAGE_CONVERT, USAGE_EXTEND, USAGE_NODE_PROOF, dst
from ..field import Field
from ..ops.aes_jax import (bitslice_keys, bitslice_pack,
                           bitslice_unpack, pack_mask, unpack_mask)
from ..ops.field_jax import FieldSpec, spec_for
from ..ops.keccak_jax import turbo_shake128_dynamic
from ..vidpf import PROOF_SIZE, CorrectionWord
from .schedule import LevelSchedule
from .xof_jax import (fixed_key_blocks, fixed_key_blocks_planes,
                      fixed_key_schedule, sample_vec, ts_prefix,
                      turboshake_xof)

_U8 = jnp.uint8

KEY_SIZE = 16

# Third backend path: route the whole level step (extend -> correct ->
# convert -> node proof) through the fused-VMEM Pallas megakernel
# (ops/level_pallas.py) instead of chaining scan-path stages.  Read
# once at import like the per-stage levers (MASTIC_KECCAK_PALLAS /
# MASTIC_AES_PALLAS in ops/); interpret mode is selected per call from
# the active backend so the CPU fabric exercises the kernel path
# bit-exactly via chained per-stage calls.
USE_LEVEL_PALLAS = os.environ.get("MASTIC_LEVEL_PALLAS", "0") == "1"


class BatchedCorrectionWords(NamedTuple):
    """Correction words for a report batch, one slice per tree level.

    seed  (R, BITS, 16) uint8
    ctrl  (R, BITS, 2) bool       [left, right]
    w     (R, BITS, VALUE_LEN, n) uint32 plain limbs
    proof (R, BITS, 32) uint8
    """
    seed: jax.Array
    ctrl: jax.Array
    w: jax.Array
    proof: jax.Array


class EvalState(NamedTuple):
    """One level's node states for a report batch: the resumable carry
    of the level loop (the reference's cache-across-rounds note,
    vidpf.py:243-245, made explicit)."""
    seed: jax.Array   # (R, N, 16) uint8
    ctrl: jax.Array   # (R, N) bool
    w: jax.Array      # (R, N, VALUE_LEN, n) uint32 plain limbs
    proof: jax.Array  # (R, N, 32) uint8


def pack_path_bits(bits_arr: jax.Array) -> jax.Array:
    """MSB-first bit packing of (..., L) bools -> (..., ceil(L/8))
    uint8 (device twin of common.pack_bits)."""
    length = bits_arr.shape[-1]
    nbytes = (length + 7) // 8
    padded = jnp.zeros(bits_arr.shape[:-1] + (nbytes * 8,), jnp.int32)
    padded = padded.at[..., :length].set(bits_arr.astype(jnp.int32))
    weights = (1 << (7 - np.arange(8))).astype(np.int32)
    grouped = padded.reshape(padded.shape[:-1] + (nbytes, 8))
    return jnp.sum(grouped * weights, axis=-1).astype(_U8)


class BatchedVidpf:
    """Batched VIDPF over `field` with input length `bits` and payload
    length `value_len` (scalar twin: mastic_tpu.vidpf.Vidpf)."""

    def __init__(self, field: type[Field], bits: int, value_len: int):
        self.field = field
        self.spec: FieldSpec = spec_for(field)
        self.BITS = bits
        self.VALUE_LEN = value_len
        # Convert reads a 16-byte next seed then VALUE_LEN elements.
        payload_bytes = value_len * self.spec.encoded_size
        self.convert_blocks = 1 + (payload_bytes + 15) // 16
        # Optional mesh-sharding hook: applied to every level's
        # EvalState so the (reports x nodes) grid stays distributed
        # (set by mastic_tpu.parallel.mesh).
        self.constrain_state = None

    # -- per-report key schedules ----------------------------------

    def roundkeys(self, ctx: bytes, nonces: jax.Array):
        """The two fixed-key AES schedules per report: (extend rk,
        convert rk), each (R, 11, 16)."""
        batch = nonces.shape[:-1]
        ext = fixed_key_schedule(dst(ctx, USAGE_EXTEND), nonces, batch)
        conv = fixed_key_schedule(dst(ctx, USAGE_CONVERT), nonces, batch)
        return (ext, conv)

    # -- the three per-node primitives -----------------------------

    def extend(self, ext_rk: jax.Array, seeds: jax.Array):
        """Extend seeds (R, N..., 16) into left/right child seeds and
        control bits (the LSB of byte 0, then cleared — reference
        vidpf.py:330-350)."""
        blocks = fixed_key_blocks(ext_rk, seeds, 2)
        (s_l, s_r) = (blocks[..., :16], blocks[..., 16:])
        t_l = (s_l[..., 0] & 1).astype(bool)
        t_r = (s_r[..., 0] & 1).astype(bool)
        mask = _U8(0xFE)
        s_l = s_l.at[..., 0].set(s_l[..., 0] & mask)
        s_r = s_r.at[..., 0].set(s_r[..., 0] & mask)
        return ((s_l, s_r), (t_l, t_r))

    def convert(self, conv_rk: jax.Array, seeds: jax.Array):
        """Convert seeds (R, N..., 16) -> (next seed, payload limbs,
        in-range mask per node) (reference vidpf.py:352-364)."""
        stream = fixed_key_blocks(conv_rk, seeds, self.convert_blocks)
        next_seed = stream[..., :16]
        (w, ok) = sample_vec(self.spec, stream, self.VALUE_LEN, offset=16)
        return (next_seed, w, ok)

    def node_proof(self, ctx: bytes, seeds: jax.Array, binder,
                   batch_shape: tuple) -> jax.Array:
        """TurboSHAKE node proof over (seed, BITS, level, path); the
        (BITS, level, path) binder is passed in pre-encoded (static for
        eval schedules, device-packed for gen)."""
        return turboshake_xof(dst(ctx, USAGE_NODE_PROOF), seeds,
                              (binder,), PROOF_SIZE, batch_shape)

    # -- key generation (client side; reference vidpf.py:103-211) --

    def _node_proof_dynamic(self, ctx: bytes, seeds: jax.Array,
                            path: jax.Array, i: jax.Array) -> jax.Array:
        """Node proof with the level index traced: the message is
        prefix | seed | BITS | le16(i) | packed path, hashed over its
        runtime length (path bytes = i//8 + 1).  Byte-exact vs the
        static node_proof for every level (the dynamic sponge masks
        the capacity tail)."""
        num_reports = seeds.shape[0]
        prefix = np.frombuffer(
            ts_prefix(dst(ctx, USAGE_NODE_PROOF), KEY_SIZE), np.uint8)
        bits_le = np.frombuffer(to_le_bytes(self.BITS, 2), np.uint8)
        i_le = jnp.stack([i & 0xFF, (i >> 8) & 0xFF]).astype(_U8)
        msg = jnp.concatenate([
            jnp.broadcast_to(jnp.asarray(prefix),
                             (num_reports, prefix.shape[0])),
            seeds,
            jnp.broadcast_to(jnp.asarray(bits_le), (num_reports, 2)),
            jnp.broadcast_to(i_le, (num_reports, 2)),
            path,
        ], axis=-1)
        length = prefix.shape[0] + KEY_SIZE + 4 + i // 8 + 1
        return turbo_shake128_dynamic(msg, jnp.int32(length), 1,
                                      PROOF_SIZE)

    def gen(self, alphas: jax.Array, betas: jax.Array, ctx: bytes,
            nonces: jax.Array, rand: jax.Array):
        """Batched VIDPF key generation.

        alphas (R, BITS) bool; betas (R, VALUE_LEN, n) plain limbs;
        nonces (R, 16); rand (R, 32) uint8.
        Returns (BatchedCorrectionWords, keys (R, 2, 16), ok (R,)).

        The level loop runs under lax.scan — the per-level body is
        identical and every shape is level-independent (the one
        varying quantity, the node-proof binder's packed on-path
        prefix, is precomputed per level and hashed with the
        runtime-length sponge), so the compiled program is O(1) in
        BITS rather than a BITS-times-unrolled graph (a 64-bit client
        program previously took minutes of XLA compile; the chain
        itself is sequential either way, reference vidpf.py:136-209).
        """
        (num_reports, bits) = alphas.shape
        assert bits == self.BITS
        (ext_rk, conv_rk) = self.roundkeys(ctx, nonces)

        keys = jnp.stack([rand[:, :KEY_SIZE], rand[:, KEY_SIZE:]], axis=1)

        # Per-level packed on-path prefixes: row i equals
        # pack_path_bits(alphas[:, :i+1]) zero-extended to capacity
        # (MSB-first packing => masking trailing bytes/bits of the
        # full packing).
        path_cap = (bits + 7) // 8
        packed_full = pack_path_bits(alphas)            # (R, cap)
        lvl = jnp.arange(bits, dtype=jnp.int32)[:, None]
        byte_idx = jnp.arange(path_cap, dtype=jnp.int32)[None, :]
        keep = jnp.left_shift(0xFF, 7 - (lvl % 8)) & 0xFF
        byte_mask = jnp.where(
            byte_idx * 8 + 7 <= lvl, 0xFF,
            jnp.where(byte_idx * 8 <= lvl, keep, 0)).astype(_U8)
        level_paths = packed_full[None] & byte_mask[:, None, :]

        def body(carry, xs):
            (s0, s1, t0, t1, ok) = carry
            (bit, path, i) = xs

            ((s0l, s0r), (t0l, t0r)) = self.extend(ext_rk, s0)
            ((s1l, s1r), (t1l, t1r)) = self.extend(ext_rk, s1)

            # The losing child's seeds are forced to collide; control
            # corrections make on-path ctrl bits shares of 1.
            sel = bit[:, None]
            seed_cw = jnp.where(sel, s0l ^ s1l, s0r ^ s1r)
            ctrl_cw_l = t0l ^ t1l ^ ~bit
            ctrl_cw_r = t0r ^ t1r ^ bit

            s0k = jnp.where(sel, s0r, s0l)
            s1k = jnp.where(sel, s1r, s1l)
            t0k = jnp.where(bit, t0r, t0l)
            t1k = jnp.where(bit, t1r, t1l)
            ctrl_cw_keep = jnp.where(bit, ctrl_cw_r, ctrl_cw_l)

            s0k = jnp.where(t0[:, None], s0k ^ seed_cw, s0k)
            t0k = t0k ^ (t0 & ctrl_cw_keep)
            s1k = jnp.where(t1[:, None], s1k ^ seed_cw, s1k)
            t1k = t1k ^ (t1 & ctrl_cw_keep)

            (seed0, w0, ok0) = self.convert(conv_rk, s0k)
            (seed1, w1, ok1) = self.convert(conv_rk, s1k)
            ok = ok & ok0 & ok1

            # Payload correction: on-path shares must sum to beta.
            w_cw = self.spec.add(self.spec.sub(betas, w0), w1)
            w_cw = jnp.where(t1k[:, None, None],
                             self.spec.neg(w_cw), w_cw)

            # Node-proof correction, binding the on-path prefix.
            proof_cw = \
                self._node_proof_dynamic(ctx, seed0, path, i) ^ \
                self._node_proof_dynamic(ctx, seed1, path, i)

            ys = (seed_cw,
                  jnp.stack([ctrl_cw_l, ctrl_cw_r], axis=-1),
                  w_cw, proof_cw)
            return ((seed0, seed1, t0k, t1k, ok), ys)

        init = (keys[:, 0], keys[:, 1],
                jnp.zeros(num_reports, bool),
                jnp.ones(num_reports, bool),
                jnp.ones(num_reports, bool))
        ((_s0, _s1, _t0, _t1, ok), ys) = jax.lax.scan(
            body, init,
            (alphas.T, level_paths, jnp.arange(bits, dtype=jnp.int32)))

        (cw_seed, cw_ctrl, cw_w, cw_proof) = ys
        cws = BatchedCorrectionWords(
            seed=jnp.moveaxis(cw_seed, 0, 1),
            ctrl=jnp.moveaxis(cw_ctrl, 0, 1),
            w=jnp.moveaxis(cw_w, 0, 1),
            proof=jnp.moveaxis(cw_proof, 0, 1),
        )
        return (cws, keys, ok)

    # -- evaluation (aggregator side; reference vidpf.py:213-325) --

    def root_state(self, agg_id: int, keys: jax.Array) -> EvalState:
        """The pre-level-0 carry: root seed = the party's key, root
        ctrl = agg_id."""
        num_reports = keys.shape[0]
        return EvalState(
            seed=keys[:, None, :],
            ctrl=jnp.full((num_reports, 1), bool(agg_id)),
            w=jnp.zeros((num_reports, 1, self.VALUE_LEN,
                         self.spec.num_limbs), jnp.uint32),
            proof=jnp.zeros((num_reports, 1, PROOF_SIZE), _U8),
        )

    def level_core(self, ext_rk: jax.Array, conv_rk: jax.Array,
                   parents: EvalState, cw_slice):
        """extend + correct + convert for one level (everything except
        the node proof): returns (next_seed (R, 2N, 16), ct (R, 2N)
        bool, w plain limbs, ok per child).  Children are interleaved
        (left0, right0, left1, right1, ...), preserving lexicographic
        order.

        Large report batches run entirely in the bitsliced plane
        domain — parent-seed pack to next-seed unpack with no byte
        round-trips in between (corrections are mask ANDs on packed
        words).  Small batches use the byte path."""
        (num_reports, num_parents) = parents.ctrl.shape
        if num_reports >= 32 and num_reports % 32 == 0:
            return self._level_core_planes(ext_rk, conv_rk, parents,
                                           cw_slice)
        (seed_cw, ctrl_cw, w_cw, _proof_cw) = cw_slice

        ((s_l, s_r), (t_l, t_r)) = self.extend(ext_rk, parents.seed)

        # Correct where the parent holds the control bit.
        sel = parents.ctrl[..., None]
        s_l = jnp.where(sel, s_l ^ seed_cw[:, None, :], s_l)
        s_r = jnp.where(sel, s_r ^ seed_cw[:, None, :], s_r)
        t_l = t_l ^ (parents.ctrl & ctrl_cw[:, None, 0])
        t_r = t_r ^ (parents.ctrl & ctrl_cw[:, None, 1])

        cs = jnp.stack([s_l, s_r], axis=2).reshape(
            num_reports, 2 * num_parents, KEY_SIZE)
        ct = jnp.stack([t_l, t_r], axis=2).reshape(
            num_reports, 2 * num_parents)

        (next_seed, w, ok) = self.convert(conv_rk, cs)
        w = jnp.where(ct[..., None, None],
                      self.spec.add(w, w_cw[:, None]), w)
        return (next_seed, ct, w, ok)

    def _level_core_planes(self, ext_rk: jax.Array, conv_rk: jax.Array,
                           parents: EvalState, cw_slice):
        """Plane-domain level core: one bitslice_pack of the parent
        seeds in, one bitslice_unpack of the next seeds + payload out."""
        (seed_cw, ctrl_cw, w_cw, _proof_cw) = cw_slice
        (num_reports, num_parents) = parents.ctrl.shape

        ext_kp = bitslice_keys(ext_rk)          # (11, 8, 16, W)
        conv_kp = bitslice_keys(conv_rk)
        sp = bitslice_pack(parents.seed)        # (8, 16, N, W)
        pctrl = pack_mask(parents.ctrl)         # (N, W)

        ext = fixed_key_blocks_planes(ext_kp, sp, 2)  # (8,16,N,2,W)
        s_l = ext[..., 0, :]
        s_r = ext[..., 1, :]
        # Control bits are plane (0, byte 0); clear them in the seeds.
        t_l = s_l[0, 0]                         # (N, W) packed bits
        t_r = s_r[0, 0]
        s_l = s_l.at[0, 0].set(jnp.zeros_like(t_l))
        s_r = s_r.at[0, 0].set(jnp.zeros_like(t_r))

        # Corrections: secret-dependent selects become mask ANDs on
        # packed words (the same constant-time discipline, denser).
        cw_planes = bitslice_pack(seed_cw)      # (8, 16, W)
        sel = cw_planes[:, :, None, :] & pctrl[None, None, :, :]
        s_l = s_l ^ sel
        s_r = s_r ^ sel
        cw_ctrl = pack_mask(ctrl_cw)            # (2, W)
        t_l = t_l ^ (pctrl & cw_ctrl[0])
        t_r = t_r ^ (pctrl & cw_ctrl[1])

        cs = jnp.stack([s_l, s_r], axis=3).reshape(
            (8, 16, 2 * num_parents) + sp.shape[-1:])
        ct_words = jnp.stack([t_l, t_r], axis=1).reshape(
            2 * num_parents, -1)

        stream = fixed_key_blocks_planes(conv_kp, cs,
                                         self.convert_blocks)
        next_seed = bitslice_unpack(stream[..., 0, :])[:num_reports]
        # Unpack payload blocks (8, 16, 2N, m-1, W) -> bytes
        # (R, 2N, (m-1)*16), block-major per node.
        tail = stream[..., 1:, :]
        tail = bitslice_unpack(
            tail.reshape(tail.shape[:2] + (-1,) + tail.shape[-1:]))
        tail = tail[:num_reports].reshape(
            num_reports, 2 * num_parents, self.convert_blocks - 1, 16)
        stream_bytes = tail.reshape(num_reports, 2 * num_parents, -1)
        (w, ok) = sample_vec(self.spec, stream_bytes, self.VALUE_LEN)

        ct = unpack_mask(ct_words, num_reports)  # (R, 2N)
        w = jnp.where(ct[..., None, None],
                      self.spec.add(w, w_cw[:, None]), w)
        return (next_seed, ct, w, ok)

    def eval_step(self, ext_rk: jax.Array, conv_rk: jax.Array,
                  parents: EvalState, cw_slice, ctx: bytes,
                  node_binder: np.ndarray):
        """One level of the tree: extend every parent, correct, convert
        and hash both children (see level_core).  Returns (EvalState
        for the children, ok (R,)).

        With MASTIC_LEVEL_PALLAS=1 and a supported shape, the whole
        level runs in the fused-VMEM megakernel (ops/level_pallas.py):
        same byte-exact outputs, but the per-eval intermediates never
        round-trip HBM (PERF.md §3's roofline lever).  Unsupported
        shapes (tiny batches, huge-payload converts, binders past one
        sponge block) keep the scan path."""
        (_seed_cw, _ctrl_cw, _w_cw, proof_cw) = cw_slice
        (num_reports, num_parents) = parents.ctrl.shape

        if USE_LEVEL_PALLAS and num_reports >= 32:
            from ..ops.level_pallas import supports
            prefix = ts_prefix(dst(ctx, USAGE_NODE_PROOF), KEY_SIZE)
            binder = np.asarray(node_binder) \
                if isinstance(node_binder, np.ndarray) else node_binder
            if supports(self.convert_blocks, len(prefix),
                        int(binder.shape[-1])):
                (child, ok) = self._eval_step_level_pallas(
                    ext_rk, conv_rk, parents, cw_slice, prefix, binder)
                if self.constrain_state is not None:
                    child = self.constrain_state(child)
                return (child, ok)

        (next_seed, ct, w, ok) = self.level_core(ext_rk, conv_rk,
                                                 parents, cw_slice)

        proof = self.node_proof(
            ctx, next_seed, jnp.asarray(node_binder),
            (num_reports, 2 * num_parents))
        proof = jnp.where(ct[..., None], proof ^ proof_cw[:, None, :],
                          proof)

        child = EvalState(seed=next_seed, ctrl=ct, w=w, proof=proof)
        if self.constrain_state is not None:
            child = self.constrain_state(child)
        return (child, jnp.all(ok, axis=-1))

    def _eval_step_level_pallas(self, ext_rk: jax.Array,
                                conv_rk: jax.Array,
                                parents: EvalState, cw_slice,
                                prefix: bytes, node_binder):
        """The megakernel level step (ops/level_pallas.py): one fused
        VMEM-resident kernel on hardware, chained per-stage kernel
        calls on the CPU fabric (the r5 interpret-validation
        technique)."""
        from ..ops.level_pallas import level_step_pallas

        (seed_cw, ctrl_cw, w_cw, proof_cw) = cw_slice
        # mastic-allow: TS004 — deliberate trace-time constant:
        # interpret mode is baked per backend and jax retraces per
        # backend, so the frozen value can never go stale
        (next_seed, ct, w, ok, proof) = level_step_pallas(
            self.spec, self.convert_blocks, ext_rk, conv_rk,
            parents.seed, parents.ctrl,
            (seed_cw, ctrl_cw, w_cw, proof_cw), prefix, node_binder,
            interpret=jax.default_backend() == "cpu")
        child = EvalState(seed=next_seed, ctrl=ct, w=w, proof=proof)
        return (child, jnp.all(ok, axis=-1))

    def eval_full(self, agg_id: int, cws: BatchedCorrectionWords,
                  keys: jax.Array, sched: LevelSchedule, ctx: bytes,
                  nonces: jax.Array):
        """Evaluate the whole grid of `sched` from the root.

        Returns (levels: list[EvalState] per depth, out_w
        (R, P, VALUE_LEN, n) payload shares in the caller's prefix
        order (negated for aggregator 1), ok (R,)).
        """
        (ext_rk, conv_rk) = self.roundkeys(ctx, nonces)
        state = self.root_state(agg_id, keys)
        ok = jnp.ones(keys.shape[0], bool)
        levels: list[EvalState] = []
        for d in range(sched.level + 1):
            pidx = sched.parent_index[d]
            if pidx is not None:
                state = EvalState(
                    seed=state.seed[:, pidx], ctrl=state.ctrl[:, pidx],
                    w=state.w[:, pidx], proof=state.proof[:, pidx])
            cw_slice = (cws.seed[:, d], cws.ctrl[:, d], cws.w[:, d],
                        cws.proof[:, d])
            (state, step_ok) = self.eval_step(
                ext_rk, conv_rk, state, cw_slice, ctx,
                sched.node_binder[d])
            ok = ok & step_ok
            levels.append(state)

        out_w = levels[sched.level].w[:, sched.out_index]
        if agg_id == 1:
            out_w = self.spec.neg(out_w)
        return (levels, out_w, ok)

    def get_beta_share(self, agg_id: int, cws: BatchedCorrectionWords,
                       keys: jax.Array, ctx: bytes, nonces: jax.Array):
        """Each party's beta share: sum of the two depth-1 payloads
        (reference vidpf.py:263-279).  Returns (share, ok)."""
        sched = LevelSchedule([(False,), (True,)], 0, self.BITS)
        (levels, _, ok) = self.eval_full(agg_id, cws, keys, sched, ctx,
                                         nonces)
        share = self.spec.add(levels[0].w[:, 0], levels[0].w[:, 1])
        if agg_id == 1:
            share = self.spec.neg(share)
        return (share, ok)

    # -- host <-> device converters (test/wire boundary) -----------

    def cws_to_host(self, cws: BatchedCorrectionWords,
                    report: int) -> list[CorrectionWord]:
        """One report's correction words as scalar-layer objects."""
        out: list[CorrectionWord] = []
        seed = np.asarray(cws.seed[report])
        ctrl = np.asarray(cws.ctrl[report])
        w = np.asarray(cws.w[report])
        proof = np.asarray(cws.proof[report])
        for d in range(self.BITS):
            w_vec = [self.field(self.spec.limbs_to_int(w[d, j]))
                     for j in range(self.VALUE_LEN)]
            out.append((seed[d].tobytes(),
                        [bool(ctrl[d, 0]), bool(ctrl[d, 1])],
                        w_vec, proof[d].tobytes()))
        return out

    def cws_from_host(self,
                      batches: list[list[CorrectionWord]],
                      ) -> BatchedCorrectionWords:
        """Scalar correction words (one list per report) -> arrays."""
        num_reports = len(batches)
        seed = np.zeros((num_reports, self.BITS, KEY_SIZE), np.uint8)
        ctrl = np.zeros((num_reports, self.BITS, 2), bool)
        w = np.zeros((num_reports, self.BITS, self.VALUE_LEN,
                      self.spec.num_limbs), np.uint32)
        proof = np.zeros((num_reports, self.BITS, PROOF_SIZE), np.uint8)
        for (r, cws) in enumerate(batches):
            for (d, (s, c, wv, p)) in enumerate(cws):
                seed[r, d] = np.frombuffer(s, np.uint8)
                ctrl[r, d] = c
                for (j, el) in enumerate(wv):
                    w[r, d, j] = self.spec.int_to_limbs(el.int())
                proof[r, d] = np.frombuffer(p, np.uint8)
        return BatchedCorrectionWords(
            seed=jnp.asarray(seed), ctrl=jnp.asarray(ctrl),
            w=jnp.asarray(w), proof=jnp.asarray(proof))

    def w_to_host(self, w: jax.Array) -> list:
        """(..., VALUE_LEN, n) plain limbs -> nested lists of scalar
        field elements."""
        # mastic-allow: TS003 — host-boundary converter: runs on
        # concrete device arrays outside any jit trace, where
        # np.asarray is the device-to-host transfer
        arr = np.asarray(w)
        if arr.ndim == 2:
            return [self.field(self.spec.limbs_to_int(arr[j]))
                    for j in range(arr.shape[0])]
        return [self.w_to_host(arr[i]) for i in range(arr.shape[0])]
