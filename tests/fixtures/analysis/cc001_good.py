"""CC001 good fixture: every cross-thread mutation holds the lock."""
import threading


class Worker:
    def __init__(self):
        self.lock = threading.Lock()
        self.items = []
        self.thread = threading.Thread(target=self._loop)

    def _loop(self):
        with self.lock:
            self.items.pop()

    def push(self, x):
        with self.lock:
            self.items.append(x)
