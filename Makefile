# CI gates (reference parity: unittest + strict mypy + examples,
# /root/reference/.github/workflows/test.yml:33-43, lint at
# lint-python.yml:24-40).
#
#   make ci      fast gate: lint + typecheck (if mypy installed) +
#                fast-tier tests (scalar + kernel smokes; <5 min cold
#                on a 1-CPU host with a warm compile cache)
#   make test    full suite (adds the slow differential/adversarial/
#                driver tiers)
#   make bench   single-chip benchmark (prints one JSON line)

PY ?= python

.PHONY: ci lint typecheck test-fast test test-slow bench

ci: lint typecheck test-fast

lint:
	$(PY) tools/lint.py

typecheck:
	@if $(PY) -c "import mypy" 2>/dev/null; then \
		$(PY) -m mypy --config-file mypy.ini mastic_tpu; \
	else \
		echo "typecheck: mypy not installed in this image;" \
		     "mypy.ini is the CI configuration (strict on the" \
		     "scalar layer) - skipping"; \
	fi

test-fast:
	$(PY) -m pytest tests/ -q -m "not slow"

test-slow:
	$(PY) -m pytest tests/ -q -m "slow"

test:
	$(PY) -m pytest tests/ -q

bench:
	$(PY) bench.py
