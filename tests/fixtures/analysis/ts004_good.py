"""Known-good: environment read once at import time (TS004)."""

import os

LEVER = os.environ.get("MASTIC_FIXTURE_LEVER", "0") == "1"


def lever() -> bool:
    return LEVER
