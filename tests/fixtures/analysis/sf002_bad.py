"""Known-bad: secret-dependent table index (SF002)."""

TABLE = tuple(range(256))


def lookup(key: bytes) -> int:
    return TABLE[key[0]]
