# CI gates (reference parity: unittest + strict mypy + examples,
# /root/reference/.github/workflows/test.yml:33-43, lint at
# lint-python.yml:24-40).
#
#   make ci      fast gate: lint + analyze + typecheck (if mypy
#                installed) + fast-tier tests (scalar + kernel
#                smokes; <5 min cold on a 1-CPU host with a warm
#                compile cache)
#   make analyze trace-safety / dtype / secret-flow / pallas /
#                robustness / observability / concurrency static
#                analyzer (tools/analysis/; rule table in USAGE.md):
#                per-file passes plus the whole-program layer (call
#                graph, CC001-CC004 thread/lock discipline,
#                SF003-SF005 interprocedural secret flow).  Exits
#                non-zero on any unsuppressed finding OR when the
#                mastic-allow total exceeds the committed baseline
#                (tools/analysis/allow_budget.json); writes the
#                SARIF 2.1.0 log to artifacts/analysis.sarif
#   make faults  fault-matrix suite for the process-separated
#                session layer (deadlines, injection, quarantine,
#                respawn; USAGE.md "Fault model & injection") —
#                fast tier only; the full-round matrix is slow-tier
#   make serve-smoke  collector-service gate (drivers/service.py):
#                fast tier of tests/test_service.py +
#                tests/test_service_overlap.py (admission,
#                backpressure, ingest faults, offline bit-identity
#                incl. mid-epoch snapshot resume, the overlapped
#                scheduler's interleaving discipline, and the
#                concurrent-submit stress matrix), the in-process
#                tools/serve.py --smoke scenario (two tenants,
#                malformed burst, overload under both shed policies,
#                deadline miss, crash drill), and the overlapped-
#                epoch drill (tools/serve.py --overlap-drill:
#                concurrent submit burst through the ingest front +
#                kill-9 + --resume with MASTIC_SERVICE_OVERLAP=2)
#   make wal-smoke  durability gate (ISSUE 18): the admission-WAL
#                tests of tests/test_wal.py (torn-tail boundary
#                matrix, group-commit ack-after-fsync, ENOSPC
#                brownout over real HTTP, snapshot-vs-WAL dedup),
#                then tools/serve.py --wal-drill — kill-9 at every
#                WAL checkpoint plus seeded disk-fault schedules,
#                bit-identity + zero lost acks + recovery
#                attribution asserted (USAGE.md "Durability")
#   make chaos-smoke  transport-security gate (ISSUE 14): the fast
#                reconnect / mTLS-negative-matrix / idle-timeout
#                tests of tests/test_net.py, then a seeded
#                --chaos-drill — full two-party collections over
#                TCP+mTLS standalone parties (tools/party.py) under
#                randomized conn_drop/partition/tls_handshake/
#                slow_loris schedules, bit-identity + recovery
#                attribution asserted (USAGE.md "Transport
#                security")
#   make net-smoke  network-front gate (mastic_tpu/net/, ISSUE 11):
#                fast tier of tests/test_net.py (DAP framing golden
#                vectors, token-bucket/connection admission, network
#                fault checkpoints, shaped transport, concurrent-
#                upload page-multiset stress), the shaped
#                leader/helper bit-identity acceptance test by
#                explicit node id, and tools/loadgen.py --smoke
#                (10^5 simulated clients against a local upload
#                endpoint: SLO held, knee degradation by policy,
#                per-IP rate limit, kill-9 mid-upload resume drill)
#   make obs-smoke  telemetry-layer gate (mastic_tpu/obs/, ISSUE 7):
#                tests/test_obs.py (spans, registry, schema, HTTP
#                status surface, tracing-on/off bit-identity) plus a
#                serve.py --smoke --status-port run that self-curls
#                /metrics, /statusz and /varz and asserts the
#                expected per-tenant series
#   make pipeline  pipelined chunk-streaming executor suite
#                (drivers/pipeline.py: serial bit-identity, overlap
#                timeline, AOT bucket compile, budget fallback) —
#                fast tier only
#   make artifacts-smoke  AOT artifact-store gate
#                (drivers/artifacts.py, ISSUE 9): fast tier of
#                tests/test_artifacts.py (digest/runtime/probe
#                gates, cache tier, runtime-skew refusal) plus
#                tools/bake.py --smoke — bake a tiny config, then a
#                FRESH subprocess completes the whole collection
#                with zero inline compiles and bit-identical
#                hitters + per-round counters vs the inline-traced
#                path
#   make multichip  mesh-sharded round suite (fast tier of
#                tests/test_mesh_pipeline.py: envelope/padding/key
#                units + per-device allocation parity) plus the REAL
#                pipelined 8-device proof run (tools/multichip.py,
#                virtual CPU devices: mesh=8 bit-identical to serial,
#                zero inline compile after round 0)
#   make test    full suite (adds the slow differential/adversarial/
#                driver tiers)
#   make bench   single-chip benchmark (prints one JSON line)

PY ?= python

.PHONY: ci lint analyze faults serve-smoke net-smoke chaos-smoke \
	wal-smoke obs-smoke pipeline artifacts-smoke multichip \
	typecheck test-fast test test-slow test-slow-1 test-slow-2 \
	test-slow-3 bench

ci: lint analyze faults serve-smoke net-smoke chaos-smoke \
	wal-smoke obs-smoke pipeline artifacts-smoke multichip \
	typecheck test-fast

faults:
	$(PY) -m pytest tests/test_faults.py -q -m "not slow"

# The offline-bit-identity + mid-epoch-resume acceptance test is
# slow-marked (it costs ~3 min of cold compile, which would blow the
# plain fast tier's budget) but runs HERE by explicit node id — it
# is this gate's acceptance test.
serve-smoke:
	$(PY) -m pytest tests/test_service.py tests/test_service_overlap.py -q -m "not slow"
	$(PY) -m pytest -q "tests/test_service.py::test_epoch_bit_identical_to_offline_with_mid_epoch_resume"
	JAX_PLATFORMS=cpu $(PY) tools/serve.py --smoke
	JAX_PLATFORMS=cpu $(PY) tools/serve.py --overlap-drill

# The shaped-parties bit-identity test is slow-marked (two full
# process-separated sessions pay real prep compiles) but runs HERE
# by explicit node id — it is this gate's acceptance test, exactly
# the serve-smoke pattern.
net-smoke:
	$(PY) -m pytest tests/test_net.py -q -m "not slow"
	$(PY) -m pytest -q "tests/test_net.py::test_shaped_parties_bit_identical_to_in_process"
	JAX_PLATFORMS=cpu $(PY) tools/loadgen.py --smoke

# The fast tier of test_net.py already ran in net-smoke; this gate
# re-runs only the ISSUE 14 transport-security selection (cheap, no
# compile) and then the real campaign: certs minted, standalone
# mTLS parties spawned, three seeded chaos schedules, bit-identity.
chaos-smoke:
	$(PY) -m pytest tests/test_net.py -q -m "not slow" \
		-k "mtls or reliable or reconnect or partition or idle_timeout or tls_config or recv_timeout"
	JAX_PLATFORMS=cpu $(PY) tools/serve.py --chaos-drill 7 --chaos-seeds 3

# The durability gate (ISSUE 18): fast WAL tests (no compile), then
# the disk-fault leg of the chaos campaign — kill-9 at every WAL
# checkpoint and seeded kill/short_write/enospc schedules, each run
# proven bit-identical with exactly the clean run's admissions.
wal-smoke:
	$(PY) -m pytest tests/test_wal.py -q -m "not slow"
	JAX_PLATFORMS=cpu $(PY) tools/serve.py --wal-drill 7 --wal-seeds 3

# The status-port smoke reuses serve.py --smoke's scenario with the
# HTTP surface armed: the run itself curls /metrics, /statusz and
# /varz and asserts the acceptance series (check_status_endpoints).
obs-smoke:
	$(PY) -m pytest tests/test_obs.py -q -m "not slow"
	JAX_PLATFORMS=cpu $(PY) tools/serve.py --smoke --status-port 0

pipeline:
	$(PY) -m pytest tests/test_pipeline.py -q -m "not slow"

artifacts-smoke:
	$(PY) -m pytest tests/test_artifacts.py -q -m "not slow"
	JAX_PLATFORMS=cpu $(PY) tools/bake.py --smoke

multichip:
	$(PY) -m pytest tests/test_mesh_pipeline.py -q -m "not slow"
	JAX_PLATFORMS=cpu $(PY) tools/multichip.py

lint:
	$(PY) tools/lint.py

analyze:
	$(PY) -m tools.analysis --stats --sarif artifacts/analysis.sarif

typecheck:
	@if $(PY) -c "import mypy" 2>/dev/null; then \
		$(PY) -m mypy --config-file mypy.ini mastic_tpu; \
	else \
		echo "typecheck: mypy not installed in this image;" \
		     "mypy.ini is the CI configuration (strict on the" \
		     "scalar layer) - skipping"; \
	fi

# test_faults' / test_service's / test_obs' / test_pipeline's /
# test_artifacts' / test_mesh_pipeline's fast tiers already ran as
# their own gates right after analyze — skip them here so `make ci`
# doesn't pay for them twice.
test-fast:
	$(PY) -m pytest tests/ -q -m "not slow" \
		--ignore=tests/test_faults.py \
		--ignore=tests/test_service.py \
		--ignore=tests/test_service_overlap.py \
		--ignore=tests/test_net.py \
		--ignore=tests/test_obs.py \
		--ignore=tests/test_pipeline.py \
		--ignore=tests/test_artifacts.py \
		--ignore=tests/test_mesh_pipeline.py \
		--ignore=tests/test_wal.py

test-slow:
	$(PY) -m pytest tests/ -q -m "slow"

# CI shards: the two halves are balanced by measured cold wall time
# (driver/incremental/chunked suites vs adversarial/backend/parallel),
# so each fits inside the 60-min job timeout even with an empty
# compile cache.  Measured cold on the 1-core build host (r5,
# fresh JAX_COMPILATION_CACHE_DIR per shard): shard 1 = 30 tests in
# 48m23s, shard 2 = 64 tests in 42m22s; warm reruns are ~10x faster.
SLOW_SHARD_1 = tests/test_drivers.py tests/test_incremental.py \
	tests/test_chunked.py tests/test_checkpoint.py \
	tests/test_metrics.py tests/test_rejection.py
test-slow-1:
	$(PY) -m pytest $(SLOW_SHARD_1) -q -m "slow"

# The mesh bit-identity matrix is its own shard: every case is a pair
# of full collection runs (~25 min cold total), which would blow
# either existing shard past the 60-min job timeout.
SLOW_SHARD_3 = tests/test_mesh_pipeline.py
test-slow-2:
	$(PY) -m pytest tests/ -q -m "slow" \
		$(foreach f,$(SLOW_SHARD_1) $(SLOW_SHARD_3),--ignore=$(f))

test-slow-3:
	$(PY) -m pytest $(SLOW_SHARD_3) -q -m "slow"

test:
	$(PY) -m pytest tests/ -q

bench:
	$(PY) bench.py
