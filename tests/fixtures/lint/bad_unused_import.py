"""Known-bad: unused import (lint check 2)."""

import os
import sys


def argv_len() -> int:
    return len(sys.argv)
