"""Known-good: the scheduler loop threads a deadline (RB005)."""


class EpochScheduler:
    def __init__(self):
        self.pending = []

    def step(self) -> bool:
        return bool(self.pending)

    def run_until_drained(self, deadline) -> bool:
        while self.step():
            if deadline.expired():
                return False
        return True
