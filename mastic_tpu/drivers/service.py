"""Continuous-ingest collector service: admission control,
backpressure, and supervised multi-tenant epochs (ROADMAP open item 1).

Every driver below this layer runs one offline batch; production
Mastic is a *stream* of uploads hitting a long-lived collector that
must stay up through malformed reports, slow tenants, overload, and
process crashes.  This module is that collector:

* **paged report buffers** — admitted uploads append to fixed-size
  pages (`ReportPage`; the ragged tail page seals at epoch cut), so
  admission is O(1) per upload and an epoch's report set is a list of
  immutable pages whose integrity is digest-checked before any page
  feeds a round (the PAPERS.md "Ragged Paged Attention" shape:
  fixed-size pages, ragged tails, admission while rounds are in
  flight);

* **admission control** — every upload blob is decode-validated at
  the door against BOTH parties' views; a malformed blob quarantines
  with the r8 reason codes (`drivers/parties.REASON_*`), and a tenant
  whose quarantine count passes its limit is suspended (its later
  uploads shed with reason ``tenant-quarantined``) so one abusive
  tenant cannot starve the rest;

* **backpressure, never silent** — per-tenant buffered reports are
  bounded (`MASTIC_SERVICE_MAX_BUFFERED`); an over-quota upload is
  shed under an explicit policy (`MASTIC_SERVICE_SHED_POLICY`):
  ``reject-newest`` refuses the incoming upload, ``oldest-epoch-first``
  drops the oldest *pending* (not yet running) epoch to make room.
  Every shed lands in `ServiceCounters.shed_reasons`;

* **epoch scheduler** — `begin_epoch` seals the tenant's buffered
  pages into an epoch; `step()` runs ONE round of one tenant's active
  epoch and round-robins across tenants, so many collection instances
  (Count / Histogram / SumVec at different bit-widths) multiplex
  through the one pipelined executor while admission continues.  The
  scheduler drives every tenant through the `CollectionRun` interface
  (heavy-hitters multi-round, attribute-metrics single-round — the
  DrJAX map/reduce shape: one `step` maps a round over the report
  axis, the aggregate is the reduce);

* **deadlines with graceful degradation** — each epoch gets a
  `Deadline` (`MASTIC_SERVICE_EPOCH_DEADLINE`, defaulting to the r8
  `MASTIC_ROUND_DEADLINE` lever); an epoch that blows it finishes at
  the last completed level and reports the truncated-but-correct
  frontier (`CollectionRun.frontier()`), marked ``truncated`` in its
  result record — degraded output over silent overrun;

* **supervision** — a round that raises is caught, counted, and
  retried a bounded number of times before the epoch is failed; the
  service keeps serving its other tenants either way;

* **crash-resume** — `to_bytes()` extends the r8 snapshot format
  (length-prefixed JSON binding header + npz payload) to cover
  buffered-but-unaggregated pages, queued and active epochs (the
  active run's own checkpoint blob rides inside), and every counter;
  `from_bytes()` restores a service that continues bit-identically
  (pages hold the original upload bytes, and the runs' checkpoint
  machinery is the r5/r8 bit-identity-proven one).  A restored
  epoch's deadline restarts fresh: the budget bounds compute per
  process lifetime, not across crashes.

Fault injection (`MASTIC_FAULTS`, party ``collector``) plugs in at
the ingest seams: checkpoint ``admit`` fires per admission attempt
(kill / hang / delay), checkpoint ``page_flush`` fires per page seal
and its ``corrupt`` / ``truncate`` actions mutate the sealed page's
stored bytes AFTER the digest is taken — modeling storage corruption,
which the digest check must catch — and checkpoints ``epoch_start`` /
``epoch_round`` / ``snapshot`` fire in the scheduler.

ISSUE 10 adds the two concurrency layers the r11 scheduler left on
the table:

* **overlapped epoch execution** (`MASTIC_SERVICE_OVERLAP` = K >= 2)
  — the scheduler keeps up to K tenants' rounds in flight by
  splitting each round at the r9 stage/collect seam
  (`CollectionRun.step_begin` dispatches without blocking,
  `step_finish` issues the round's one blocking sync): tenant B's
  host-side stage (page decode, upload prep, AOT program fetch,
  dispatch) runs while tenant A's dispatched round computes on
  device.  Rounds of one tenant never overlap each other, so every
  tenant's round sequence — and therefore its results — is
  bit-identical to the serial round-robin path; run kinds without a
  split seam (chunked runs, whose intra-round pipeline owns the sync
  discipline) execute atomically inside their quantum, named in the
  service metrics.  `to_bytes()` drains in-flight rounds first: a
  snapshot is always a quiescent point;

* **concurrent ingest front** (`MASTIC_SERVICE_INGEST_THREADS` >= 1)
  — `submit()` becomes a bounded-queue enqueue
  (`MASTIC_SERVICE_INGEST_QUEUE`; a full queue sheds with reason
  ``ingest-queue-full``, counted, never silent) and a small worker
  pool decode-validates both party views off-thread, landing sealed
  pages into the same digest-sealed buffers under the tenant's
  admission lock — so `submit()` never blocks on round execution and
  admission no longer serializes with the scheduler.  Shed policies,
  quotas, and quarantine semantics are unchanged; every counter
  increment is race-safe (`ServiceCounters` locks itself, tenant
  buffer state mutates only under `_Tenant.lock`), which the r13
  concurrency pass (CC001-CC004) proves over the whole program.
"""

import abc
import hashlib
import json
import queue as queue_mod
import threading
import time
from dataclasses import dataclass
from typing import Optional

import numpy as np

from .. import wire
from ..metrics import ServiceCounters
from ..obs import trace as obs_trace
from ..obs.registry import get_registry
from . import faults as faults_mod
from .pipeline import overlap_efficiency
from .session import Deadline, _env_float, _env_int
from .parties import (REASON_MALFORMED, REASON_NAMES, REASON_RANGE,
                      instantiate)
from .attribute_metrics import AttributeMetricsRun
from .heavy_hitters import HeavyHittersRun

# Page-integrity failure: the page's stored bytes no longer match the
# digest taken at seal time (storage corruption; the `page_flush`
# fault models it).  Extends the r8 per-report reason codes.
REASON_PAGE_CORRUPT = 3
SERVICE_REASON_NAMES = dict(REASON_NAMES)
SERVICE_REASON_NAMES[REASON_PAGE_CORRUPT] = "page-corrupt"

SHED_POLICIES = ("reject-newest", "oldest-epoch-first")

# submit() outcomes.
ADMITTED = "admitted"
QUARANTINED = "quarantined"
SHED = "shed"
# With the concurrent ingest front armed, submit() enqueues and the
# admission verdict lands asynchronously (in the counters / events);
# a caller that needs the verdict synchronously runs with the front
# off, exactly as before.
QUEUED = "queued"

_SNAPSHOT_VERSION = 1


# -- the scheduler-facing run interface -------------------------------

class CollectionRun(abc.ABC):
    """What the epoch scheduler needs from a collection run — the one
    interface the heavy-hitters multi-round loop, the chunked
    streaming loop (both via `HeavyHittersRun`), and the
    attribute-metrics single round (`AttributeMetricsRun`) all stand
    behind.  `HeavyHittersRun` predates this ABC and is registered as
    a virtual subclass; its checkpoint machinery is the bit-identity
    contract the service snapshot rides on.
    """

    done: bool
    metrics: list

    @abc.abstractmethod
    def step(self) -> bool:
        """Run one round; True while more rounds remain."""

    @abc.abstractmethod
    def result(self):
        """The collection's final output (valid once `done`)."""

    @abc.abstractmethod
    def frontier(self) -> list:
        """The truncated-but-correct output after the last COMPLETED
        round — what a deadline-missed epoch reports.  Every entry
        passed all checks of every completed round; nothing about
        rounds that never ran is claimed."""

    @abc.abstractmethod
    def rounds_completed(self) -> int:
        """Rounds completed over the run's LIFETIME — unlike
        `len(metrics)`, this survives checkpoint-resume (the metrics
        list only covers rounds run in this process)."""

    @abc.abstractmethod
    def to_bytes(self) -> bytes:
        """Checkpoint between rounds (resume must be bit-identical)."""

    # Optional split-phase protocol (ISSUE 10): runs that can split a
    # round at the stage/collect seam additionally provide
    #   step_begin() -> handle | None   (dispatch, non-blocking; the
    #                                    handle's "atomic" flag is
    #                                    True when the round ran
    #                                    outright instead)
    #   step_finish(handle) -> bool     (blocking sync + advance)
    # with step() == step_begin()+step_finish().  The overlapped
    # epoch executor feature-detects them (getattr) so legacy run
    # kinds — and test stubs — keep working atomically.


CollectionRun.register(HeavyHittersRun)
CollectionRun.register(AttributeMetricsRun)

MODES = ("heavy_hitters", "attribute_metrics")


# -- configuration ----------------------------------------------------

def _env_str(name: str, default: str) -> str:
    import os

    raw = os.environ.get(name)
    return default if raw is None or not raw.strip() else raw.strip()


@dataclass
class ServiceConfig:
    """Service-wide levers (env forms in USAGE.md "Collector
    service").  Per-tenant overrides live on `TenantSpec`."""

    page_size: int = 64           # reports per buffer page
    max_buffered: int = 4096      # per-tenant admitted-but-unfinished
    max_pending_epochs: int = 4   # per-tenant queued (not running)
    shed_policy: str = "reject-newest"
    quarantine_limit: int = 64    # per-tenant; past it, suspend
    epoch_deadline: float = 1800.0
    epoch_retries: int = 1        # extra attempts for a failing round
    overlap: int = 0              # tenants' rounds in flight (<2 =
    #                               serial round-robin, the r11 path)
    ingest_threads: int = 0       # concurrent ingest front (0 = off:
    #                               submit() admits in-process)
    ingest_queue: int = 256       # bounded ingest queue (uploads)

    def __post_init__(self):
        if self.shed_policy not in SHED_POLICIES:
            raise ValueError(
                f"unknown shed policy {self.shed_policy!r} (must be "
                f"one of {', '.join(SHED_POLICIES)})")
        if self.page_size < 1:
            raise ValueError("page_size must be >= 1")
        if self.ingest_queue < 1:
            raise ValueError("ingest_queue must be >= 1")
        if self.overlap < 0 or self.ingest_threads < 0:
            raise ValueError("overlap / ingest_threads must be >= 0")

    @classmethod
    def from_env(cls) -> "ServiceConfig":
        return cls(
            page_size=_env_int("MASTIC_SERVICE_PAGE_SIZE", 64),
            max_buffered=_env_int("MASTIC_SERVICE_MAX_BUFFERED", 4096),
            max_pending_epochs=_env_int("MASTIC_SERVICE_MAX_EPOCHS", 4),
            shed_policy=_env_str("MASTIC_SERVICE_SHED_POLICY",
                                 "reject-newest"),
            quarantine_limit=_env_int("MASTIC_SERVICE_QUARANTINE_LIMIT",
                                      64),
            epoch_deadline=_env_float(
                "MASTIC_SERVICE_EPOCH_DEADLINE",
                _env_float("MASTIC_ROUND_DEADLINE", 1800.0)),
            epoch_retries=_env_int("MASTIC_SERVICE_EPOCH_RETRIES", 1),
            overlap=_env_int("MASTIC_SERVICE_OVERLAP", 0),
            ingest_threads=_env_int("MASTIC_SERVICE_INGEST_THREADS",
                                    0),
            ingest_queue=_env_int("MASTIC_SERVICE_INGEST_QUEUE", 256),
        )


@dataclass
class TenantSpec:
    """One collection instance (tenant) the service multiplexes.

    `spec` is the r8 party-config instantiation record
    ({"class": "MasticCount", "args": [8]}); `mode` picks the run
    kind; `thresholds` (heavy hitters) / `attributes` (attribute
    metrics) parameterize it.  Optional overrides fall back to the
    service config."""

    name: str
    spec: dict
    ctx: bytes
    verify_key: bytes
    mode: str = "heavy_hitters"
    thresholds: Optional[dict] = None
    attributes: Optional[list] = None
    chunk_size: Optional[int] = None
    page_size: Optional[int] = None
    max_buffered: Optional[int] = None
    epoch_deadline: Optional[float] = None
    quarantine_limit: Optional[int] = None

    def __post_init__(self):
        if self.mode not in MODES:
            raise ValueError(f"unknown tenant mode {self.mode!r} "
                             f"(must be one of {', '.join(MODES)})")
        if self.mode == "heavy_hitters" and not self.thresholds:
            raise ValueError(f"tenant {self.name}: heavy_hitters mode "
                             f"needs thresholds")
        if self.mode == "attribute_metrics" and not self.attributes:
            raise ValueError(f"tenant {self.name}: attribute_metrics "
                             f"mode needs attributes")

    def to_json(self) -> dict:
        return {
            "name": self.name, "spec": self.spec,
            "ctx": self.ctx.hex(), "verify_key": self.verify_key.hex(),
            "mode": self.mode,
            "thresholds": (None if self.thresholds is None
                           else thresholds_to_json(self.thresholds)),
            "attributes": self.attributes,
            "chunk_size": self.chunk_size,
            "page_size": self.page_size,
            "max_buffered": self.max_buffered,
            "epoch_deadline": self.epoch_deadline,
            "quarantine_limit": self.quarantine_limit,
        }

    @classmethod
    def from_json(cls, data: dict) -> "TenantSpec":
        return cls(
            name=data["name"], spec=data["spec"],
            ctx=bytes.fromhex(data["ctx"]),
            verify_key=bytes.fromhex(data["verify_key"]),
            mode=data["mode"],
            thresholds=(None if data["thresholds"] is None
                        else thresholds_from_json(data["thresholds"])),
            attributes=data["attributes"],
            chunk_size=data["chunk_size"],
            page_size=data["page_size"],
            max_buffered=data["max_buffered"],
            epoch_deadline=data["epoch_deadline"],
            quarantine_limit=data["quarantine_limit"],
        )


def thresholds_to_json(thresholds: dict) -> dict:
    """Prefix-tuple keys -> bit strings ("default" passes through)."""
    out = {}
    for (k, v) in thresholds.items():
        if k == "default":
            out[k] = v
        else:
            out["".join("1" if b else "0" for b in k)] = v
    return out


def thresholds_from_json(data: dict) -> dict:
    out = {}
    for (k, v) in data.items():
        if k == "default":
            out[k] = v
        else:
            out[tuple(c == "1" for c in k)] = v
    return out


# -- upload codec (both parties' views in one blob) -------------------

def encode_upload(mastic, report) -> bytes:
    """One client upload as the service ingests it: both aggregators'
    wire-encoded views, framed back to back (clients talk to the
    aggregators directly in a full deployment; the service here is
    the ingest door of the co-located pair)."""
    (nonce, public_share, input_shares) = report
    return (wire.frame(wire.encode_report(mastic, 0, nonce,
                                          public_share,
                                          input_shares[0]))
            + wire.frame(wire.encode_report(mastic, 1, nonce,
                                            public_share,
                                            input_shares[1])))


def decode_upload(mastic, blob: bytes) -> tuple:
    """Validate + decode one upload blob into the drivers' report
    tuple.  Raises ValueError on any malformation — the admission
    path turns that into a reason-coded quarantine."""
    (b0, rest) = wire.unframe(blob)
    (b1, rest) = wire.unframe(rest)
    if rest:
        raise ValueError(f"{len(rest)} trailing bytes after the "
                         f"helper view")
    (nonce0, ps0, share0) = wire.decode_report(mastic, 0, b0)
    (nonce1, _ps1, share1) = wire.decode_report(mastic, 1, b1)
    if nonce0 != nonce1:
        raise ValueError("nonce mismatch between the party views")
    head = mastic.NONCE_SIZE + wire.public_share_size(mastic)
    if b0[:head] != b1[:head]:
        raise ValueError("public share mismatch between the party "
                         "views")
    return (nonce0, ps0, [share0, share1])


def _decode_reason(exc: Exception) -> int:
    """The r8 reason taxonomy (drivers/parties.load_reports)."""
    return (REASON_RANGE if "out of range" in str(exc)
            else REASON_MALFORMED)


# -- paged report buffers ---------------------------------------------

class ReportPage:
    """A fixed-size page of admitted upload blobs.  Open pages accept
    appends; `seal()` freezes the page behind a SHA-256 digest of its
    framed payload, verified every time the page's bytes feed a round
    or cross a snapshot — a corrupted page is detected and dropped,
    never silently aggregated."""

    __slots__ = ("blobs", "count", "payload", "digest")

    def __init__(self):
        self.blobs: list = []
        self.count = 0
        self.payload: Optional[bytes] = None
        self.digest: Optional[bytes] = None

    def append(self, blob: bytes) -> None:
        if self.payload is not None:
            raise ValueError("page is sealed")
        self.blobs.append(blob)
        self.count += 1

    def seal(self) -> None:
        if self.payload is not None:
            return
        self.payload = b"".join(wire.frame(b) for b in self.blobs)
        self.digest = hashlib.sha256(self.payload).digest()
        self.blobs = []

    def verify(self) -> bool:
        if self.payload is None:
            return True   # open page: bytes never left this process
        return hashlib.sha256(self.payload).digest() == self.digest

    def decode_blobs(self) -> list:
        """The page's upload blobs (sealed pages unframe their stored
        payload; digest must be verified by the caller first)."""
        if self.payload is None:
            return list(self.blobs)
        (out, rest) = ([], self.payload)
        while rest:
            (blob, rest) = wire.unframe(rest)
            out.append(blob)
        return out

    @classmethod
    def from_payload(cls, payload: bytes, digest: bytes,
                     count: int) -> "ReportPage":
        page = cls()
        page.payload = payload
        page.digest = digest
        page.count = count
        return page


class _Epoch:
    """One sealed collection epoch: the pages cut from the tenant's
    buffer at begin_epoch, plus (once scheduled) the live run."""

    __slots__ = ("epoch_id", "pages", "run", "reports", "deadline",
                 "failures", "started_at", "reports_lost", "span")

    def __init__(self, epoch_id: int, pages: list):
        self.epoch_id = epoch_id
        self.pages = pages
        self.run = None
        self.reports: Optional[list] = None   # decoded at start
        self.deadline: Optional[Deadline] = None
        self.failures = 0
        self.started_at: Optional[float] = None
        self.reports_lost = 0   # dropped by page-corruption detection
        self.span = None        # open "epoch" trace span while active

    def report_count(self) -> int:
        return sum(p.count for p in self.pages)


class _Tenant:
    """One tenant's state AND its admission path (ISSUE 10): the
    quota / quarantine / page machinery lives here, on the tenant,
    because ingest workers and the scheduler thread both walk it —
    every buffer mutation happens under `self.lock`, the effective
    limits are resolved once at construction (spec override falling
    back to the service config), and the ServiceCounters ledger locks
    itself."""

    __slots__ = ("spec", "mastic", "open_page", "sealed", "pending",
                 "active", "completed", "counters", "epoch_seq",
                 "suspended", "last_timeline", "lock",
                 "eff_page_size", "eff_max_buffered",
                 "eff_quarantine_limit", "eff_epoch_deadline",
                 "eff_shed_policy", "replay_digests")

    def __init__(self, spec: TenantSpec, config: ServiceConfig):
        self.spec = spec
        self.mastic = instantiate(spec.spec)
        self.open_page = ReportPage()
        self.sealed: list = []      # sealed pages awaiting an epoch
        self.pending: list = []     # [_Epoch] queued, oldest first
        self.active: Optional[_Epoch] = None
        self.completed: list = []   # epoch result records (dicts)
        self.counters = ServiceCounters(tenant=spec.name)
        # Every tenant's Prometheus series exist from boot (at zero)
        # so a scrape before the first event still sees the family.
        self.counters.export_registry()
        self.epoch_seq = 0
        self.suspended = False
        self.last_timeline: Optional[list] = None  # statusz surface
        # The admission lock (ISSUE 10): every mutation of the
        # tenant's buffer state (open_page, sealed, pending,
        # suspended, active) happens under it — ingest workers land
        # pages while the scheduler thread cuts epochs and retires
        # them.  Pure reads (occupancy gauges) stay lock-free.
        self.lock = threading.Lock()
        # Effective limits, resolved once: admission never has to
        # reach back into the (main-thread-owned) service config.
        self.eff_page_size = spec.page_size or config.page_size
        self.eff_max_buffered = (spec.max_buffered
                                 or config.max_buffered)
        self.eff_quarantine_limit = (
            spec.quarantine_limit
            if spec.quarantine_limit is not None
            else config.quarantine_limit)
        self.eff_epoch_deadline = (
            spec.epoch_deadline if spec.epoch_deadline is not None
            else config.epoch_deadline)
        self.eff_shed_policy = config.shed_policy
        # SHA-256 digests of reports the WAL replayed at recovery
        # (ISSUE 18): a client retrying an upload that was durable
        # but never acked lands here and gets an idempotent ADMITTED
        # ack instead of a duplicate buffer entry.  Empty except in
        # a freshly recovered process, so the hot path costs one
        # truthiness check.
        self.replay_digests: set = set()

    def buffered_reports(self) -> int:
        """Reports the tenant holds admitted-but-unfinished — the
        number the admission quota bounds (open + sealed pages,
        queued epochs, and the running epoch)."""
        total = self.open_page.count \
            + sum(p.count for p in self.sealed) \
            + sum(ep.report_count() for ep in self.pending)
        if self.active is not None:
            total += self.active.report_count()
        return total

    # -- admission (any thread; ISSUE 10) --------------------------

    def admit_decoded(self, blob: bytes,
                      decode_exc: Optional[Exception],
                      injector=None) -> tuple:
        """The admission verdict, under the tenant's lock: suspended
        -> shed; malformed -> reason-coded quarantine (suspension
        past the limit); over-quota -> shed policy; else land in the
        open page.  Trace events emit after the lock releases, and a
        full page seals outside it (the digest hash and the
        page_flush fault — which may legitimately stall — must not
        hold up concurrent admission)."""
        name = self.spec.name
        events: list = []
        to_seal: Optional[ReportPage] = None
        with self.lock:
            if self.suspended:
                self.counters.inc("shed")
                self.counters.bump_shed("tenant-quarantined")
                verdict = (SHED, "tenant-quarantined")
                events.append(
                    ("shed", {"tenant": name,
                              "reason": "tenant-quarantined"}))
            elif decode_exc is not None:
                reason = SERVICE_REASON_NAMES[
                    _decode_reason(decode_exc)]
                self.counters.inc("quarantined")
                self.counters.bump_quarantine(reason)
                events.append(("quarantine", {"tenant": name,
                                              "reason": reason}))
                if self.counters.quarantined \
                        >= self.eff_quarantine_limit:
                    self.suspended = True
                    events.append((
                        "tenant_suspended",
                        {"tenant": name,
                         "quarantined": self.counters.quarantined}))
                verdict = (QUARANTINED, reason)
            else:
                verdict = None
                if self.buffered_reports() >= self.eff_max_buffered:
                    # oldest-epoch-first may make room by dropping a
                    # queued epoch; if the buffer is still over quota
                    # after that (or the policy is reject-newest),
                    # the incoming upload sheds.
                    self.shed_oldest()
                    if self.buffered_reports() \
                            >= self.eff_max_buffered:
                        self.counters.inc("shed")
                        self.counters.bump_shed("reject-newest")
                        events.append(
                            ("shed", {"tenant": name,
                                      "reason": "reject-newest"}))
                        verdict = (SHED, "reject-newest")
                if verdict is None:
                    self.open_page.append(blob)
                    self.counters.inc("admitted")
                    if self.open_page.count >= self.eff_page_size:
                        to_seal = self.open_page
                        self.open_page = ReportPage()
                    verdict = (ADMITTED, "")
        if to_seal is not None:
            self.seal_page(to_seal, injector)
        for (ev_name, attrs) in events:
            obs_trace.event(ev_name, **attrs)
        return verdict

    def shed_oldest(self) -> Optional[str]:
        """Over-quota relief under the tenant's effective policy
        (caller holds `self.lock`).  Returns the shed detail when
        room was made (oldest-epoch-first), None when the incoming
        upload itself must be rejected."""
        if self.eff_shed_policy != "oldest-epoch-first" \
                or not self.pending:
            return None
        victim = self.pending.pop(0)
        lost = victim.report_count()
        self.counters.inc("shed", lost)
        self.counters.bump_shed("oldest-epoch-first", lost)
        obs_trace.event("shed", tenant=self.spec.name,
                        reason="oldest-epoch-first", reports=lost,
                        epoch=victim.epoch_id)
        return f"oldest-epoch-first dropped epoch {victim.epoch_id} " \
               f"({lost} reports)"

    def count_front_shed(self, reason: str, n: int = 1) -> None:
        """One front-door (network-admission, ISSUE 11) refusal into
        this tenant's shed ledger — rate limit, connection ceiling,
        body-size gate, truncated body.  The door's policy decisions
        and the service's read as one accounting (the ledger locks
        itself; buffer state is untouched)."""
        self.counters.inc("shed", n)
        self.counters.bump_shed(reason, n)
        obs_trace.event("shed", tenant=self.spec.name, reason=reason)

    def seal_page(self, page: ReportPage, injector=None) -> None:
        """Seal one just-swapped-out page behind its digest and
        append it to the sealed list.  Called WITHOUT the lock — the
        page left the open slot atomically, so no other thread can
        reach it, and the `page_flush` fault's delay/hang actions
        must stall only this admission, not the tenant."""
        page.seal()
        if injector is not None:
            # One fault event per seal: kill/hang/delay fire as
            # process faults, truncate/corrupt mutate the stored
            # bytes AFTER the digest (storage-corruption model — the
            # verify() gate must catch it downstream).
            page.payload = injector.on_blob("page_flush",
                                            page.payload)
        with self.lock:
            self.sealed.append(page)
        self.counters.inc("pages_sealed")


# -- the concurrent ingest front --------------------------------------

class _IngestFront:
    """The admission thread pool (ISSUE 10): `submit()` enqueues raw
    upload blobs into a BOUNDED queue and returns immediately;
    workers pop, decode-validate both party views (the expensive wire
    work, outside any lock), and land the verdict through the
    tenant's admission lock — so admission never blocks on round
    execution and the scheduler thread never pays upload decode.

    Bounds and failure behavior: the queue holds at most
    `ServiceConfig.ingest_queue` uploads (a full queue is the
    caller's shed, reason ``ingest-queue-full`` — counted by
    `CollectorService.submit`, never silent); `flush()` blocks until
    every queued upload has fully landed (the epoch-cut barrier);
    `stop()` retires the workers.  Workers are daemon threads: a
    crashing process never hangs on them, and the service snapshot
    flushes first so no admitted upload is in limbo at snapshot
    time."""

    def __init__(self, svc: "CollectorService", threads: int,
                 queue_bound: int):
        self._svc = svc
        self.queue: queue_mod.Queue = queue_mod.Queue(
            maxsize=max(1, queue_bound))
        self._stop = threading.Event()
        self.threads = [
            threading.Thread(target=self._worker,
                             name=f"mastic-ingest-{i}", daemon=True)
            for i in range(max(1, threads))
        ]
        for th in self.threads:
            th.start()

    def offer(self, tenant: str, blob: bytes) -> bool:
        """Enqueue one upload; False when the bounded queue is full
        (the caller sheds, attributed)."""
        try:
            self.queue.put_nowait((tenant, blob))
            return True
        except queue_mod.Full:
            return False

    def _worker(self) -> None:
        # The 0.1 s poll bounds the loop (stop() lands within one
        # tick); queue.get itself carries the timeout, so a stopped
        # front never wedges on an empty queue.
        while not self._stop.is_set():
            try:
                item = self.queue.get(timeout=0.1)
            except queue_mod.Empty:
                item = None
            if item is None:
                continue
            (tenant, blob) = item
            try:
                self._svc._ingest_one(tenant, blob)
            except Exception as exc:
                # A worker must survive anything one hostile upload
                # can throw — the blob is dropped ATTRIBUTED (decode
                # errors proper are quarantined inside _ingest_one;
                # this is the belt over it).
                obs_trace.event("ingest_error", tenant=tenant,
                                error=type(exc).__name__)
            finally:
                self.queue.task_done()

    def flush(self) -> None:
        """Block until every enqueued upload has fully landed (pages
        appended, counters settled) — the barrier `begin_epoch` and
        the snapshot run before touching buffered state."""
        self.queue.join()

    def stop(self) -> None:
        self._stop.set()
        for th in self.threads:
            th.join(timeout=5.0)


# -- the service ------------------------------------------------------

class CollectorService:
    """The long-lived, supervised multi-tenant collector (module
    docstring has the full story).  Single-threaded by design: one
    `step()` is one scheduler quantum (one round of one tenant's
    active epoch), and `submit()` may be called between quanta —
    admission lands in the open page, so uploads arriving while
    rounds are in flight join the NEXT epoch."""

    def __init__(self, tenants: list, config: Optional[ServiceConfig]
                 = None, injector=None, mesh=None):
        self.config = config or ServiceConfig.from_env()
        self.mesh = mesh
        self.injector = (injector if injector is not None
                         else faults_mod.injector_from_env("collector"))
        self.tenants: dict = {}
        for spec in tenants:
            if spec.name in self.tenants:
                raise ValueError(f"duplicate tenant {spec.name!r}")
            self.tenants[spec.name] = _Tenant(spec, self.config)
        self._rr = 0   # round-robin cursor over tenant order
        self.resumed = False
        # Guards the tenant table itself: add_tenant publishes a new
        # entry while ingest workers look tenants up by name.
        self._tenants_mu = threading.Lock()
        # Overlapped epoch executor state (ISSUE 10): in-flight
        # staged rounds, oldest first — owned by the scheduler
        # thread; at most one entry per tenant.
        self._inflight: list = []
        self._sched_window: Optional[dict] = None
        # Concurrent ingest front: armed by config, stoppable
        # (stop_ingest) so tests and drains can quiesce it.
        self._ingest: Optional[_IngestFront] = None
        if self.config.ingest_threads > 0:
            self._ingest = _IngestFront(self,
                                        self.config.ingest_threads,
                                        self.config.ingest_queue)
        # Warm AOT artifact store (drivers/artifacts.py): preload
        # every tenant's program family at boot so the first epoch of
        # each never traces — the ROADMAP item 4 enabler for epoch
        # overlap and containerized serving.
        for t in self.tenants.values():
            self._preload_artifacts(t)

    def stop_ingest(self) -> None:
        """Quiesce the ingest front: land everything queued, retire
        the workers.  Idempotent; submit() admits in-process after.
        The unpublish happens under the control-plane mutex — an
        HTTP handler thread may be mid-submit reading `_ingest`
        (ISSUE 11), and a torn read there would route its upload
        around the queue the caller just flushed."""
        if self._ingest is not None:
            self._ingest.flush()
            self._ingest.stop()
            with self._tenants_mu:
                self._ingest = None

    def flush_ingest(self) -> None:
        """Barrier: every upload submitted so far has fully landed
        (admitted / quarantined / shed) when this returns."""
        if self._ingest is not None:
            self._ingest.flush()

    def inflight_rounds(self) -> int:
        """Staged-but-uncollected rounds (0 outside overlap mode —
        the serve.py snapshot cadence keys on this)."""
        return len(self._inflight)

    def add_tenant(self, spec: TenantSpec) -> None:
        """Admit a new collection tenant into the running service
        (fresh buffers/counters; uploads may `submit()` immediately).
        Its artifact family preloads right here, so with a baked
        store the new tenant's first round pays disk loads at
        admission time, not a trace at epoch time."""
        if spec.name in self.tenants:
            raise ValueError(f"duplicate tenant {spec.name!r}")
        t = _Tenant(spec, self.config)
        with self._tenants_mu:
            self.tenants[spec.name] = t
        self._preload_artifacts(t)

    def _preload_artifacts(self, t: _Tenant) -> None:
        """Pull the tenant's program family (instantiation + ctx)
        from the AOT store into memory — digest-gated and probe-
        verified per artifact (artifacts.ArtifactStore.load); every
        outcome lands in mastic_artifact_loads_total."""
        from ..backend.mastic_jax import BatchedMastic
        from . import artifacts

        store = artifacts.store_from_env()
        if store is None:
            return
        fam = artifacts.family_id(BatchedMastic(t.mastic), t.spec.ctx)
        counts = store.preload(lambda key: key[-1] == fam)
        if counts:
            obs_trace.event("artifact_preload", tenant=t.spec.name,
                            store=store.path, **counts)

    def _checkpoint(self, step: str) -> None:
        if self.injector is not None:
            self.injector.checkpoint(step)

    # -- admission -------------------------------------------------

    def submit(self, tenant: str, blob: bytes) -> tuple:
        """Admit one upload blob for `tenant`.  Returns (status,
        detail): ADMITTED, QUARANTINED (detail = reason name), SHED
        (detail = policy / reason), or — with the concurrent ingest
        front armed — QUEUED (the verdict lands asynchronously in the
        counters).  Never raises for bad input — a hostile upload
        must cost the service one decode, not an exception path."""
        t = self.tenants[tenant]
        if t.replay_digests:
            # Post-recovery only (ISSUE 18): a retry of an upload the
            # WAL already replayed must ack exactly-once, not buffer
            # a duplicate.
            digest = hashlib.sha256(blob).digest()
            with t.lock:
                duplicate = digest in t.replay_digests
            if duplicate:
                obs_trace.event("duplicate_ack", tenant=tenant)
                return (ADMITTED, "duplicate")
        if self._ingest is not None:
            # The front path: enqueue only.  submit() never blocks on
            # decode OR round execution; a full queue is explicit
            # backpressure, shed with its own reason.
            if self._ingest.offer(tenant, blob):
                return (QUEUED, "")
            t.count_front_shed("ingest-queue-full")
            return (SHED, "ingest-queue-full")
        return self._ingest_one(tenant, blob)

    def shed_external(self, tenant: str, reason: str,
                      n: int = 1) -> None:
        """One front-door refusal (ISSUE 11: the network admission
        layer) attributed into the tenant's shed ledger exactly like
        an in-service shed — `_Tenant.count_front_shed` has the
        story.  Unknown tenants can't reach here (the front 404s
        before a ledger exists to blame)."""
        self.tenants[tenant].count_front_shed(reason, n)

    def report_digests(self, tenant: str) -> set:
        """SHA-256 digests of every upload blob the tenant currently
        buffers (open page, sealed pages, queued and active epochs) —
        the WAL recovery dedup baseline: a record both in the restored
        snapshot and in the log must not be buffered twice.  Pages
        failing their digest check contribute nothing (their reports
        are already lost to the corruption-drop path)."""
        t = self.tenants[tenant]
        with t.lock:
            pages = [t.open_page] + list(t.sealed)
            for ep in t.pending:
                pages.extend(ep.pages)
            if t.active is not None:
                pages.extend(t.active.pages)
            digests = set()
            for page in pages:
                if not page.verify():
                    continue
                for blob in page.decode_blobs():
                    digests.add(hashlib.sha256(blob).digest())
        return digests

    def note_replayed(self, tenant: str, digest: bytes) -> None:
        """Register one WAL-replayed report digest for retry dedup
        (see `_Tenant.replay_digests`)."""
        t = self.tenants[tenant]
        with t.lock:
            t.replay_digests.add(digest)

    def _ingest_one(self, tenant: str, blob: bytes) -> tuple:
        """Decode-validate one upload and land the verdict — the
        in-process submit body, also the ingest workers' unit of
        work.  Decode runs OUTSIDE the admission lock (it is the
        expensive part and touches no shared state); everything that
        mutates tenant buffers goes through _Tenant.admit_decoded."""
        t = self.tenants[tenant]
        self._checkpoint("admit")
        decode_exc: Optional[Exception] = None
        if not t.suspended:
            # Racy pre-check only — it saves the decode for a
            # suspended tenant; admit_decoded re-checks under the
            # lock either way.
            try:
                decode_upload(t.mastic, blob)
            except (ValueError, EOFError) as exc:
                decode_exc = exc
        return t.admit_decoded(blob, decode_exc,
                               injector=self.injector)

    # -- epochs ----------------------------------------------------

    def begin_epoch(self, tenant: str) -> Optional[int]:
        """Cut the tenant's buffered pages into a new pending epoch.
        Returns the epoch id, or None when there is nothing buffered
        or the pending queue is full under reject-newest (the pages
        stay buffered for a later cut).  With the ingest front armed
        the cut flushes the queue first, so every upload submitted
        before the cut is in or ahead of this epoch — never lost in
        the queue."""
        t = self.tenants[tenant]
        self.flush_ingest()
        with t.lock:
            to_seal: Optional[ReportPage] = None
            if t.open_page.count:
                to_seal = t.open_page
                t.open_page = ReportPage()
        if to_seal is not None:
            t.seal_page(to_seal, self.injector)
        with t.lock:
            if not t.sealed:
                return None
            if len(t.pending) >= self.config.max_pending_epochs:
                if t.shed_oldest() is None:
                    # reject-newest: the cut is refused (pages stay
                    # buffered for a later attempt), counted, not
                    # silent.
                    t.counters.inc("epochs_refused")
                    return None
            epoch = _Epoch(t.epoch_seq, t.sealed)
            t.epoch_seq += 1
            t.sealed = []
            t.pending.append(epoch)
            return epoch.epoch_id

    def _build_run(self, t: _Tenant, reports: list) -> CollectionRun:
        spec = t.spec
        if spec.mode == "heavy_hitters":
            run = HeavyHittersRun(
                t.mastic, spec.ctx, spec.thresholds, reports,
                verify_key=spec.verify_key,
                chunk_size=spec.chunk_size, mesh=self.mesh)
        else:
            run = AttributeMetricsRun(
                t.mastic, spec.ctx, spec.attributes, reports,
                verify_key=spec.verify_key,
                chunk_size=spec.chunk_size, mesh=self.mesh)
        # The run's round spans / registry series carry this tenant.
        run.obs_tenant = spec.name
        return run

    def _restore_run(self, t: _Tenant, reports: list,
                     blob: bytes) -> CollectionRun:
        spec = t.spec
        if spec.mode == "heavy_hitters":
            run = HeavyHittersRun.from_bytes(
                t.mastic, spec.ctx, spec.thresholds, reports,
                spec.verify_key, blob, mesh=self.mesh)
        else:
            run = AttributeMetricsRun.from_bytes(
                t.mastic, spec.ctx, spec.attributes, reports,
                spec.verify_key, blob, chunk_size=spec.chunk_size,
                mesh=self.mesh)
        run.obs_tenant = spec.name
        return run

    def _epoch_reports(self, t: _Tenant, epoch: _Epoch) -> list:
        """Decode the epoch's pages into the drivers' report tuples,
        dropping (and counting) any page whose digest check fails —
        a corrupted page degrades the epoch, never poisons it."""
        reports = []
        surviving = []
        for page in epoch.pages:
            if not page.verify():
                epoch.reports_lost += page.count
                t.counters.inc("pages_corrupt")
                t.counters.inc("quarantined", page.count)
                t.counters.bump_quarantine(
                    SERVICE_REASON_NAMES[REASON_PAGE_CORRUPT],
                    page.count)
                obs_trace.event(
                    "page_corrupt", tenant=t.spec.name,
                    epoch=epoch.epoch_id, reports=page.count)
                continue
            surviving.append(page)
            for blob in page.decode_blobs():
                # Admission already validated the blob; decode again
                # so the run consumes exactly the persisted bytes.
                reports.append(decode_upload(t.mastic, blob))
        with t.lock:
            # The page list feeds report_count(), which ingest
            # workers read through the admission quota.
            epoch.pages = surviving
        return reports

    def _start_epoch(self, t: _Tenant) -> None:
        with t.lock:
            epoch = t.pending.pop(0)
        self._checkpoint("epoch_start")
        epoch.span = obs_trace.get_tracer().start_detached_span(
            "epoch", tenant=t.spec.name, epoch=epoch.epoch_id,
            reports=epoch.report_count())
        reports = self._epoch_reports(t, epoch)
        if not reports:
            # Every page was corrupt (or the epoch was empty): an
            # immediately-final degraded epoch, counted, not raised.
            t.counters.inc("epochs_started")
            t.counters.inc("epochs_failed")
            t.completed.append(self._record(t, epoch, result=[],
                                            truncated=True,
                                            levels=0, error="no "
                                            "surviving reports"))
            return
        epoch.reports = reports
        t.counters.inc("epochs_started")
        try:
            epoch.run = self._build_run(t, reports)
        except Exception as exc:
            # Run construction can refuse (e.g. a memory-envelope
            # gate for the tenant's chunk config): a config-sick
            # tenant fails ITS epoch, attributably — not the service.
            t.counters.inc("epochs_failed")
            t.completed.append(self._record(
                t, epoch, result=[], truncated=True, levels=0,
                error=f"{type(exc).__name__}: {exc}"))
            return
        epoch.deadline = Deadline(t.eff_epoch_deadline)
        epoch.started_at = time.monotonic()
        with t.lock:
            t.active = epoch

    def _record(self, t: _Tenant, epoch: _Epoch, result,
                truncated: bool, levels: int,
                error: Optional[str] = None) -> dict:
        rec = {
            "tenant": t.spec.name,
            "epoch": epoch.epoch_id,
            "reports": epoch.report_count(),
            "reports_lost": epoch.reports_lost,
            "result": _jsonable(result),
            "truncated": truncated,
            "levels_completed": levels,
        }
        if epoch.run is not None and epoch.run.metrics:
            # Compile accounting over the epoch's rounds (this
            # process's): the zero-steady-state-compile claim is
            # checkable per epoch record, not just per live run —
            # bench.py --service-overlap asserts it.
            inline = 0
            compile_ms = 0.0
            for mx in epoch.run.metrics:
                art = mx.extra.get("artifacts") or {}
                inline += int(art.get("inline_compiles", 0))
                pipe = mx.extra.get("pipeline") or {}
                compile_ms += float(pipe.get("compile_inline_ms",
                                             0.0))
                for chunk in mx.extra.get("chunks") or ():
                    compile_ms += float(
                        chunk.get("phases", {}).get("compile_ms",
                                                    0.0))
            rec["inline_compiles"] = inline
            rec["compile_ms"] = round(compile_ms, 2)
        if epoch.started_at is not None:
            rec["wall_s"] = round(time.monotonic() - epoch.started_at,
                                  3)
        if error is not None:
            rec["error"] = error
        if epoch.span is not None:
            # The epoch's trace span closes with its outcome; every
            # round span of the epoch parented to it.
            epoch.span.set(truncated=truncated, levels=levels,
                           **({"error": error} if error else {}))
            obs_trace.get_tracer().end_span(epoch.span)
            epoch.span = None
        return rec

    # -- the scheduler ---------------------------------------------

    def step(self) -> bool:
        """One scheduler quantum.  Serial (overlap < 2): pick the
        next tenant (round-robin) with work, run one round of its
        active epoch (starting the oldest pending epoch if none is
        active).  Overlapped (overlap = K >= 2): keep up to K
        tenants' rounds in flight — stage rounds into the in-flight
        window round-robin, then collect the oldest staged round's
        blocking sync, so tenant B's host-side stage (page decode,
        upload prep, AOT program fetch, dispatch) runs while tenant
        A's dispatched round computes on device.  Returns whether any
        tenant still has epoch work queued, running, or in flight."""
        if self.config.overlap >= 2:
            return self._step_overlapped()
        names = list(self.tenants)
        for off in range(len(names)):
            t = self.tenants[names[(self._rr + off) % len(names)]]
            if t.active is None and t.pending:
                self._start_epoch(t)
            if t.active is None:
                continue
            self._rr = (self._rr + off + 1) % len(names)
            self._run_one_round(t)
            break
        self._publish_sched_gauges()
        return any(t.active is not None or t.pending
                   for t in self.tenants.values())

    def _step_overlapped(self) -> bool:
        """One overlapped quantum: fill the in-flight window (at most
        one staged round per tenant — a tenant's rounds never overlap
        each other, which is what keeps its results bit-identical to
        the serial path), then collect the OLDEST in-flight round.
        Atomic run kinds (no split seam) execute whole during their
        stage slot; the device still computes another tenant's staged
        round underneath them."""
        names = list(self.tenants)
        staged = {name for (name, _e) in self._inflight}
        for off in range(len(names)):
            if len(self._inflight) >= self.config.overlap:
                break
            name = names[(self._rr + off) % len(names)]
            if name in staged:
                continue
            t = self.tenants[name]
            if t.active is None and t.pending:
                self._start_epoch(t)
            if t.active is None:
                continue
            entry = self._stage_quantum(t)
            if entry is not None:
                self._inflight.append((name, entry))
                staged.add(name)
        if len(names):
            self._rr = (self._rr + 1) % len(names)
        if self._inflight:
            (name, entry) = self._inflight.pop(0)
            t = self.tenants[name]
            entry["gap_ms"] = (time.perf_counter()
                               - entry["staged_at"]) * 1e3
            self._collect_quantum(t, entry)
        self._publish_sched_gauges()
        return bool(self._inflight) \
            or any(t.active is not None or t.pending
                   for t in self.tenants.values())

    def _stage_quantum(self, t: _Tenant) -> Optional[dict]:
        """Stage one round of the tenant's active epoch: deadline
        gate, then `step_begin` under the epoch span.  Returns the
        in-flight entry, or None when the quantum resolved inline
        (deadline truncation, atomic round, epoch completion, or a
        supervised failure)."""
        epoch = t.active
        self._checkpoint("epoch_round")
        tracer = obs_trace.get_tracer()
        if epoch.deadline.expired():
            self._truncate_epoch(t, epoch)
            return None
        t0 = time.perf_counter()
        before = len(epoch.run.metrics)
        begin = getattr(epoch.run, "step_begin", None)
        try:
            with tracer.use_parent(epoch.span):
                if begin is None:
                    # Legacy / stub run kind: no split seam — run the
                    # whole round as one atomic quantum.
                    more = epoch.run.step()
                    self._after_round(t, epoch, before, t0, more)
                    self._sched_busy((time.perf_counter() - t0) * 1e3)
                    return None
                handle = begin()
        except Exception as exc:   # supervised: fail the epoch, not
            # the service — other tenants keep their schedule
            self._round_failed(t, epoch, exc)
            return None
        stage_ms = (time.perf_counter() - t0) * 1e3
        self._sched_busy(stage_ms)
        if handle is None:
            # The run had no round left (a resumed, already-final
            # run): the epoch completes without touching the device.
            self._complete_epoch(t, epoch)
            return None
        entry = {"handle": handle, "t0": t0, "before": before,
                 "staged_at": time.perf_counter(), "gap_ms": 0.0}
        if handle.get("atomic"):
            # The whole round already ran inside begin (chunked runs
            # own their sync discipline): finish it now — deferring
            # would only delay the frontier advance.
            self._collect_quantum(t, entry)
            return None
        return entry

    def _collect_quantum(self, t: _Tenant, entry: dict) -> None:
        """Collect one staged round: `step_finish` (the round's one
        blocking sync) under the epoch span, then the shared
        post-round bookkeeping."""
        epoch = t.active
        tracer = obs_trace.get_tracer()
        t0 = time.perf_counter()
        try:
            with tracer.use_parent(epoch.span):
                more = epoch.run.step_finish(entry["handle"])
        except Exception as exc:
            self._round_failed(t, epoch, exc)
            return
        collect_ms = (time.perf_counter() - t0) * 1e3
        self._sched_busy(collect_ms + entry["gap_ms"])
        self._after_round(t, epoch, entry["before"], entry["t0"],
                          more)

    def _run_one_round(self, t: _Tenant) -> None:
        epoch = t.active
        self._checkpoint("epoch_round")
        tracer = obs_trace.get_tracer()
        if epoch.deadline.expired():
            self._truncate_epoch(t, epoch)
            return
        t0 = time.perf_counter()
        before = len(epoch.run.metrics)
        try:
            # The run's own round span (HeavyHittersRun.step /
            # AttributeMetricsRun.step) parents to this tenant's open
            # epoch span — NOT to whatever epoch started last.
            with tracer.use_parent(epoch.span):
                more = epoch.run.step()
        except Exception as exc:   # supervised: fail the epoch, not
            # the service — other tenants keep their schedule
            self._round_failed(t, epoch, exc)
            return
        self._after_round(t, epoch, before, t0, more)

    def _truncate_epoch(self, t: _Tenant, epoch: _Epoch) -> None:
        """Graceful degradation: finish at the last completed level;
        the frontier is correct for every round that ran."""
        t.counters.inc("deadline_misses")
        t.counters.inc("epochs_truncated")
        if epoch.span is not None:
            epoch.span.event("deadline_miss",
                             levels=epoch.run.rounds_completed())
        t.completed.append(self._record(
            t, epoch, result=epoch.run.frontier(),
            truncated=True,
            levels=epoch.run.rounds_completed()))
        with t.lock:
            t.active = None

    def _round_failed(self, t: _Tenant, epoch: _Epoch,
                      exc: Exception) -> None:
        """Supervision: count the failure; past the retry budget the
        epoch fails with its truncated frontier, otherwise the run is
        REBUILT from the epoch's pages — a round that raises
        mid-execution (staged or collected) can leave the runner's
        device carries inconsistent, and prep is a pure function of
        the reports, so the restart is bit-identical (completed
        levels recompute; the r8 respawn-and-replay model applied
        in-process)."""
        epoch.failures += 1
        if epoch.failures > self.config.epoch_retries:
            t.counters.inc("epochs_failed")
            t.completed.append(self._record(
                t, epoch, result=epoch.run.frontier(),
                truncated=True,
                levels=epoch.run.rounds_completed(),
                error=f"{type(exc).__name__}: {exc}"))
            with t.lock:
                t.active = None
        else:
            if epoch.span is not None:
                epoch.span.event(
                    "epoch_retry", attempt=epoch.failures,
                    cause=f"{type(exc).__name__}: {exc}"[:200])
            get_registry().counter(
                "mastic_session_retries_total",
                tenant=t.spec.name).inc()
            epoch.run = self._build_run(t, epoch.reports)

    def _after_round(self, t: _Tenant, epoch: _Epoch, before: int,
                     t0: float, more: bool) -> None:
        """Shared post-round bookkeeping for the serial and
        overlapped paths: counters, the per-round service block,
        occupancy gauges, epoch completion."""
        t.counters.inc("rounds")
        quantum_ms = (time.perf_counter() - t0) * 1e3
        reg = get_registry()
        for mx in epoch.run.metrics[before:]:
            round_ms = mx.extra.get("round_wall_ms", 0.0)
            sched_ms = round(max(0.0, quantum_ms - round_ms), 3)
            mx.extra["service"] = {
                "tenant": t.spec.name,
                "epoch": epoch.epoch_id,
                "sched_overhead_ms": sched_ms,
                "buffered_reports": t.buffered_reports(),
                "pending_epochs": len(t.pending),
                # Overlap context: staged rounds in flight when this
                # round retired (0 = the serial r11 schedule).
                "overlap_inflight": len(self._inflight),
            }
            # The service block joins the unified extra schema
            # (re-stamp: the driver already validated its own blocks).
            mx.validate_extra()
            reg.counter("mastic_sched_overhead_ms_total",
                        tenant=t.spec.name).inc(sched_ms)
            if mx.extra.get("chunks"):
                t.last_timeline = mx.extra["chunks"]
        reg.gauge("mastic_buffered_reports",
                  tenant=t.spec.name).set(t.buffered_reports())
        reg.gauge("mastic_pending_epochs",
                  tenant=t.spec.name).set(len(t.pending))
        if not more:
            self._complete_epoch(t, epoch)

    def _complete_epoch(self, t: _Tenant, epoch: _Epoch) -> None:
        t.counters.inc("epochs_completed")
        t.completed.append(self._record(
            t, epoch, result=epoch.run.result(), truncated=False,
            levels=epoch.run.rounds_completed()))
        with t.lock:
            t.active = None

    # -- overlap accounting (occupancy + efficiency series) --------

    def _sched_busy(self, ms: float) -> None:
        """Accumulate scheduler busy time (stage work, collect work,
        and in-flight device windows) into the current overlap
        window.  Windows open at the first staged work and close when
        the scheduler drains; busy > wall means staged device time
        was hidden under other tenants' work."""
        w = self._sched_window
        if w is None:
            w = self._sched_window = {"t0": time.perf_counter(),
                                      "busy_ms": 0.0}
        w["busy_ms"] += ms

    def _publish_sched_gauges(self) -> None:
        reg = get_registry()
        occupancy = len(self._inflight)
        reg.gauge("mastic_scheduler_occupancy").set(occupancy)
        if self._ingest is not None:
            reg.gauge("mastic_ingest_queue_depth").set(
                self._ingest.queue.qsize())
        if self._sched_window is not None and not self._inflight \
                and not any(t.active is not None or t.pending
                            for t in self.tenants.values()):
            # Window closed: stamp the structural overlap efficiency
            # (pipeline.overlap_efficiency semantics — 0.0 when
            # nothing overlapped, the hidden fraction otherwise).
            w = self._sched_window
            wall_ms = (time.perf_counter() - w["t0"]) * 1e3
            eff = overlap_efficiency(
                [{"phases": {"busy_ms": w["busy_ms"]}}], wall_ms)
            reg.gauge("mastic_sched_overlap_efficiency").set(eff)
            self._sched_window = None

    def run_until_drained(self,
                          deadline: Optional[Deadline] = None) -> bool:
        """Drive the scheduler until no epoch work remains.  Returns
        False when `deadline` expired first (remaining work stays
        queued — snapshot and resume, or keep stepping)."""
        while self.step():
            if deadline is not None and deadline.expired():
                return False
        return True

    def drained(self) -> bool:
        return not self._inflight \
            and not any(t.active is not None or t.pending
                        for t in self.tenants.values())

    def _drain_inflight(self) -> None:
        """Collect every staged round (oldest first) so the service
        reaches a quiescent point — the snapshot precondition: a
        half-staged round serializes neither consistently nor
        portably, so `to_bytes` retires them first (the same rounds
        would recompute bit-identically after a crash anyway)."""
        pending = list(self._inflight)
        self._inflight = []
        for (name, entry) in pending:
            t = self.tenants[name]
            entry["gap_ms"] = (time.perf_counter()
                               - entry["staged_at"]) * 1e3
            self._collect_quantum(t, entry)

    # -- observability ---------------------------------------------

    def metrics(self) -> dict:
        """The service metrics JSON: per-tenant counters, buffer
        occupancy, quarantine/shed reason tables, epoch records."""
        out = {"policy": self.config.shed_policy,
               "resumed": self.resumed,
               "overlap": self.config.overlap,
               "ingest_threads": self.config.ingest_threads,
               "inflight_rounds": len(self._inflight),
               "tenants": {}}
        for (name, t) in self.tenants.items():
            out["tenants"][name] = {
                "buffered_reports": t.buffered_reports(),
                "open_page": t.open_page.count,
                "sealed_pages": len(t.sealed),
                "pending_epochs": len(t.pending),
                "active_epoch": (t.active.epoch_id
                                 if t.active is not None else None),
                "suspended": t.suspended,
                "counters": t.counters.as_dict(),
                "epochs": list(t.completed),
                # The statusz last-round timeline (per-chunk phases
                # of the tenant's most recent chunked round).
                "last_round_timeline": t.last_timeline,
            }
        return out

    # -- snapshot / resume -----------------------------------------

    def to_bytes(self) -> bytes:
        """Snapshot everything a crash must not lose: buffered pages
        (open + sealed), queued epochs, the active epoch's pages and
        its run checkpoint, completed results, and counters — the r8
        snapshot format (length-prefixed JSON binding header + npz
        payload), extended to the ingest layer.  The snapshot is a
        quiescent point (ISSUE 10): the ingest queue flushes first
        (every upload submitted before the snapshot fully lands),
        in-flight overlapped rounds collect (a half-staged round's
        device futures serialize neither consistently nor portably —
        and would recompute bit-identically after a crash anyway),
        and each tenant's buffers then serialize under its admission
        lock so a concurrent submit can never tear a page across the
        npz arrays."""
        import io

        self.flush_ingest()
        self._drain_inflight()
        self._checkpoint("snapshot")
        header = json.dumps({
            "version": _SNAPSHOT_VERSION,
            "policy": self.config.shed_policy,
            "tenants": [t.spec.to_json()
                        for t in self.tenants.values()],
        }, sort_keys=True).encode()
        data: dict = {"meta": np.array(
            [_SNAPSHOT_VERSION, len(self.tenants)], np.int64)}

        def put_page(prefix: str, page: ReportPage) -> None:
            sealed = page.payload is not None
            payload = (page.payload if sealed
                       else b"".join(wire.frame(b)
                                     for b in page.blobs))
            data[prefix] = np.frombuffer(payload, np.uint8)
            data[prefix + "_meta"] = np.array(
                [page.count, int(sealed)], np.int64)
            data[prefix + "_digest"] = np.frombuffer(
                page.digest if sealed else b"\x00" * 32, np.uint8)

        def put_epoch(prefix: str, epoch: _Epoch) -> None:
            data[prefix + "_meta"] = np.array(
                [epoch.epoch_id, len(epoch.pages),
                 epoch.reports_lost], np.int64)
            for (j, page) in enumerate(epoch.pages):
                put_page(f"{prefix}_pg{j}", page)

        for (i, t) in enumerate(self.tenants.values()):
            with t.lock:
                data[f"t{i}_state"] = np.array(
                    [t.epoch_seq, int(t.suspended), len(t.sealed),
                     len(t.pending), int(t.active is not None)],
                    np.int64)
                data[f"t{i}_counters"] = np.frombuffer(
                    json.dumps(t.counters.as_dict()).encode(),
                    np.uint8)
                data[f"t{i}_completed"] = np.frombuffer(
                    json.dumps(t.completed).encode(), np.uint8)
                put_page(f"t{i}_open", t.open_page)
                for (j, page) in enumerate(t.sealed):
                    put_page(f"t{i}_s{j}", page)
                for (k, epoch) in enumerate(t.pending):
                    put_epoch(f"t{i}_p{k}", epoch)
                if t.active is not None:
                    put_epoch(f"t{i}_active", t.active)
                    data[f"t{i}_active_run"] = np.frombuffer(
                        t.active.run.to_bytes(), np.uint8)
        buf = io.BytesIO()
        np.savez(buf, **data)
        return (len(header).to_bytes(4, "little") + header
                + buf.getvalue())

    @classmethod
    def from_bytes(cls, data: bytes,
                   config: Optional[ServiceConfig] = None,
                   injector=None, mesh=None) -> "CollectorService":
        """Restore a snapshotted service.  Page digests are verified
        as epochs start (a snapshot corrupted in storage degrades the
        affected epoch, detected, instead of aggregating garbage);
        the active epoch's run resumes bit-identically from its own
        checkpoint blob.  Its deadline restarts fresh — the budget
        bounds compute per process lifetime."""
        import io

        hlen = int.from_bytes(data[:4], "little")
        try:
            header = json.loads(data[4:4 + hlen])
        except ValueError:
            raise ValueError(
                "service snapshot has no JSON binding header — not a "
                "snapshot written by CollectorService.to_bytes")
        if header.get("version") != _SNAPSHOT_VERSION:
            raise ValueError(f"unknown service snapshot version "
                             f"{header.get('version')}")
        arrays = np.load(io.BytesIO(data[4 + hlen:]),
                         allow_pickle=False)
        specs = [TenantSpec.from_json(d) for d in header["tenants"]]
        if config is None:
            config = ServiceConfig.from_env()
        config.shed_policy = header["policy"]
        svc = cls(specs, config=config, injector=injector, mesh=mesh)
        svc.resumed = True

        def get_page(prefix: str) -> ReportPage:
            payload = arrays[prefix].tobytes()
            (count, sealed) = [int(x)
                               for x in arrays[prefix + "_meta"]]
            digest = arrays[prefix + "_digest"].tobytes()
            if sealed:
                return ReportPage.from_payload(payload, digest, count)
            page = ReportPage()
            rest = payload
            while rest:   # mastic-allow: RB005 — bounded by the
                # stored open-page payload length
                (blob, rest) = wire.unframe(rest)
                page.append(blob)
            return page

        def get_epoch(prefix: str) -> _Epoch:
            (epoch_id, npages, lost) = [
                int(x) for x in arrays[prefix + "_meta"]]
            epoch = _Epoch(epoch_id, [get_page(f"{prefix}_pg{j}")
                                      for j in range(npages)])
            epoch.reports_lost = lost
            return epoch

        for (i, t) in enumerate(svc.tenants.values()):
            (seq, susp, nsealed, npending, has_active) = [
                int(x) for x in arrays[f"t{i}_state"]]
            # Under the admission lock: a restored service's ingest
            # front is already live, so the buffer swap must be
            # atomic against a concurrent submit.
            with t.lock:
                t.epoch_seq = seq
                t.suspended = bool(susp)
                restored = json.loads(
                    arrays[f"t{i}_counters"].tobytes())
                # Pre-ISSUE-7 snapshots carry no tenant label.
                restored.setdefault("tenant", t.spec.name)
                t.counters = ServiceCounters.from_dict(restored)
                t.counters.resumes += 1
                t.completed = json.loads(
                    arrays[f"t{i}_completed"].tobytes())
                t.open_page = get_page(f"t{i}_open")
                t.sealed = [get_page(f"t{i}_s{j}")
                            for j in range(nsealed)]
                t.pending = [get_epoch(f"t{i}_p{k}")
                             for k in range(npending)]
            # Republish the persisted totals so the Prometheus series
            # continue where the crashed process left them.
            t.counters.export_registry()
            if has_active:
                epoch = get_epoch(f"t{i}_active")
                reports = svc._epoch_reports(t, epoch)
                if not reports:
                    t.counters.inc("epochs_failed")
                    t.completed.append(svc._record(
                        t, epoch, result=[], truncated=True,
                        levels=0, error="no surviving reports after "
                        "resume"))
                else:
                    epoch.reports = reports
                    epoch.run = svc._restore_run(
                        t, reports, arrays[f"t{i}_active_run"]
                        .tobytes())
                    epoch.deadline = Deadline(t.eff_epoch_deadline)
                    epoch.started_at = time.monotonic()
                    epoch.span = obs_trace.get_tracer() \
                        .start_detached_span(
                            "epoch", tenant=t.spec.name,
                            epoch=epoch.epoch_id,
                            reports=epoch.report_count(),
                            resumed=True)
                    with t.lock:
                        t.active = epoch
        return svc


def _jsonable(result):
    """Epoch results as JSON-safe values (heavy-hitter prefixes are
    bool tuples; attribute aggregates are (name, value) pairs)."""
    if isinstance(result, (list, tuple)):
        return [_jsonable(x) for x in result]
    if isinstance(result, (bool, np.bool_)):
        return bool(result)
    if isinstance(result, (int, np.integer)):
        return int(result)
    return result
