"""Whole-program model for the interprocedural passes (ISSUE 8).

The per-file passes (tracesafe/dtypes/pallasck/...) see one AST at a
time; the concurrency pass (CC001-CC004) and the whole-program
secret-flow rules (SF003-SF005) need to know who calls whom, which
functions run on which thread, and which statements run under which
lock.  `Program` is that model, built ONCE per analyzer run from the
same parsed `FileInfo`s every per-file pass consumes (each source
file is parsed exactly once per run).

What the model resolves — and, just as important, what it knowingly
does not (the blind spots are documented in USAGE.md):

* **call edges** — bare intra-module calls, `from x import f` calls,
  module-alias attribute calls (`wire.frame(...)`), `self.m()` /
  `cls.m()` method calls (following statically-known single bases),
  locally-constructed receivers (`x = Tracer(); x.span(...)`),
  receivers stored on `self` by `__init__` (`self._httpd = ...`),
  and nested `def`s.  Receivers the above cannot type fall back to
  *method-name dispatch*: the call edges to EVERY known class
  defining that method name, capped at `DISPATCH_CAP` targets so a
  generic name (`get`, `close`) does not connect the world.  Dynamic
  dispatch past the cap, `getattr`, decorators that swap callables,
  and functions passed as values (callbacks) are NOT followed.

* **thread roots** — `threading.Thread(target=...)` targets, the
  handler classes of `*HTTPServer`/`*TCPServer` constructions (their
  `do_*`/`handle*`/`log_message` methods run on server threads), and
  process entry points (module bodies, which cover the
  `if __name__ == "__main__"` subprocess entries of parties.py and
  tools/serve.py).  Every function gets the set of *root groups*
  that reach it: the main group (module bodies plus API entry points
  — functions no analyzed code calls), and one group per discovered
  thread root.

* **lock discipline** — lock identities (module globals and `self.X`
  attributes assigned from `threading.Lock()`/`RLock()`), the
  `with <lock>:` regions of every function, and the *inherited* lock
  set: a function whose every analyzed call site runs under lock L
  holds L on entry (a must-analysis to fixpoint over the call graph
  — how `MetricsRegistry._child`'s mutations are recognized as
  guarded by the caller's `with self._lock`).
"""

import ast

from .core import dotted

# A method name resolving (by name) to more than this many classes is
# treated as dynamic dispatch and not followed.
DISPATCH_CAP = 8

# Names shared with builtin container/str/file methods: an unknown
# receiver calling one of these is almost always a dict/list/str/file,
# not the one repo class that happens to define the same name — never
# name-dispatch them.
NO_DISPATCH = {"append", "appendleft", "extend", "add", "update",
               "pop", "popleft", "get", "items", "keys", "values",
               "setdefault", "clear", "remove", "insert", "sort",
               "index", "count", "copy", "join", "split", "strip",
               "encode", "decode", "format", "close", "write",
               "read", "readline", "flush", "hex", "tobytes",
               "put", "send", "recv"}

_LOCK_CTORS = {"Lock", "RLock"}
_MUTABLE_CTORS = {"dict", "list", "set", "deque", "defaultdict",
                  "OrderedDict", "Counter", "bytearray"}


def module_of(rel: str) -> str:
    """Dotted module name for a repo-relative path; files outside the
    package roots (fixtures) use their stem."""
    if rel.endswith(".py"):
        rel = rel[:-3]
    if rel.endswith("/__init__"):
        rel = rel[: -len("/__init__")]
    if rel.startswith(("mastic_tpu/", "tools/")) or "/" not in rel:
        return rel.replace("/", ".")
    return rel.rsplit("/", 1)[1]


class FuncNode:
    """One function scope (module-level def, method, nested def, or
    the module body pseudo-scope)."""

    __slots__ = ("qual", "module", "rel", "node", "cls", "name",
                 "is_module", "callees", "callers", "weak_calls")

    def __init__(self, qual, module, rel, node, cls, name,
                 is_module=False):
        self.qual = qual
        self.module = module
        self.rel = rel
        self.node = node
        self.cls = cls            # ClassNode or None
        self.name = name
        self.is_module = is_module
        self.callees: list = []   # (ast.Call, (FuncNode, ...))
        self.callers: list = []   # (FuncNode, ast.Call)
        # id(call) of callees resolved only by multi-candidate
        # method-name dispatch — too coarse for thread reachability
        # and return-taint lookup (the consumers treat them as
        # unresolved-but-connected).
        self.weak_calls: set = set()

    def params(self) -> list:
        if self.is_module:
            return []
        a = self.node.args
        return [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]


class ClassNode:
    __slots__ = ("qual", "module", "rel", "name", "node", "methods",
                 "bases", "attr_classes", "mutable_attrs",
                 "lock_attrs")

    def __init__(self, qual, module, rel, name, node):
        self.qual = qual
        self.module = module
        self.rel = rel
        self.name = name
        self.node = node
        self.methods: dict = {}       # name -> FuncNode
        self.bases: list = []         # base-name strings
        self.attr_classes: dict = {}  # attr -> ClassNode | str (ext)
        self.mutable_attrs: set = set()   # attrs init'd to containers
        self.lock_attrs: set = set()      # attrs init'd to Lock()


class _Scope:
    """Iterates one function scope's own statements (nested defs are
    their own FuncNodes)."""

    @staticmethod
    def iter(node):
        stack = list(ast.iter_child_nodes(node))
        while stack:
            sub = stack.pop()
            yield sub
            if not isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef,
                                    ast.ClassDef)):
                stack.extend(ast.iter_child_nodes(sub))


class Program:
    """The whole-program model.  Build once from the run's FileInfos;
    every whole-program pass consumes the same instance."""

    def __init__(self, infos):
        self.infos = {info.rel: info for info in infos}
        self.functions: dict = {}        # qual -> FuncNode
        self.classes: dict = {}          # qual -> ClassNode
        self.methods_by_name: dict = {}  # name -> [FuncNode]
        self.classes_by_name: dict = {}  # bare name -> [ClassNode]
        # (module, local name) -> ("func"|"class"|"module", qual)
        self.names: dict = {}
        self.module_bodies: dict = {}    # module -> FuncNode
        self.thread_roots: dict = {}     # group id -> [FuncNode]
        self.roots_of: dict = {}         # qual -> set of group ids
        self.lock_ids: set = set()
        self.entry_locks: dict = {}      # qual -> frozenset(lock ids)
        self._regions_cache: dict = {}
        self._ctor_cache: dict = {}      # qual -> {local: ctor name}
        self._collect()
        self._resolve_imports()
        self._resolve_edges()
        self._discover_threads()
        self._reachability()
        self._lock_fixpoint()

    # -- collection ------------------------------------------------

    def _collect(self) -> None:
        for info in self.infos.values():
            mod = module_of(info.rel)
            body = FuncNode(mod + ".<module>", mod, info.rel,
                            info.tree, None, "<module>",
                            is_module=True)
            self.module_bodies[mod] = body
            self.functions[body.qual] = body
            for node in info.tree.body:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    self._add_function(info, mod, node, None)
                elif isinstance(node, ast.ClassDef):
                    self._add_class(info, mod, node)
        # Second phase: receiver typing needs every class collected
        # first (cross-module constructor references).
        for cls in self.classes.values():
            self._scan_init(cls)

    def _add_class(self, info, mod, node) -> None:
        qual = f"{mod}.{node.name}"
        cls = ClassNode(qual, mod, info.rel, node.name, node)
        cls.bases = [dotted(b) for b in node.bases]
        self.classes[qual] = cls
        self.classes_by_name.setdefault(node.name, []).append(cls)
        self.names[(mod, node.name)] = ("class", qual)
        for sub in node.body:
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn = self._add_function(info, mod, sub, cls)
                cls.methods[sub.name] = fn
                self.methods_by_name.setdefault(
                    sub.name, []).append(fn)

    def _add_function(self, info, mod, node, cls, prefix=None):
        base = prefix or (cls.qual if cls else mod)
        qual = f"{base}.{node.name}"
        fn = FuncNode(qual, mod, info.rel, node, cls, node.name)
        self.functions[qual] = fn
        if cls is None and prefix is None:
            self.names[(mod, node.name)] = ("func", qual)
        # Nested defs become their own scopes, addressable from the
        # enclosing one (closures like serve.py's put_page).
        for sub in _Scope.iter(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._add_function(info, mod, sub, cls,
                                   prefix=qual + ".<locals>")
        return fn

    def _scan_init(self, cls: ClassNode) -> None:
        """Receiver types, mutable-container attrs and lock attrs a
        class binds on `self` (any method; __init__ dominates)."""
        for fn in cls.methods.values():
            for node in _Scope.iter(fn.node):
                if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                value = node.value
                for t in targets:
                    if not (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        continue
                    attr = t.attr
                    ctor = self._ctor_name(value)
                    if ctor is None:
                        continue
                    if ctor in _LOCK_CTORS:
                        cls.lock_attrs.add(attr)
                        self.lock_ids.add(("attr", cls.qual, attr))
                    elif ctor in _MUTABLE_CTORS:
                        cls.mutable_attrs.add(attr)
                    else:
                        known = self.classes_by_name.get(ctor)
                        cls.attr_classes[attr] = (
                            known[0] if known and len(known) == 1
                            else ctor)
                if isinstance(value, (ast.Dict, ast.List, ast.Set)):
                    for t in targets:
                        if isinstance(t, ast.Attribute) \
                                and isinstance(t.value, ast.Name) \
                                and t.value.id == "self":
                            cls.mutable_attrs.add(t.attr)

    @staticmethod
    def _ctor_name(value):
        if isinstance(value, ast.Call):
            name = dotted(value.func)
            return name.rsplit(".", 1)[-1] if name else None
        return None

    # -- imports ----------------------------------------------------

    def _resolve_imports(self) -> None:
        # Two sweeps: re-exports (A imports a name B itself imported)
        # resolve on the second.
        for _ in range(2):
            self._import_sweep()

    def _import_sweep(self) -> None:
        modules = {module_of(rel) for rel in self.infos}
        for info in self.infos.values():
            mod = module_of(info.rel)
            pkg = mod.rsplit(".", 1)[0] if "." in mod else ""
            for node in ast.walk(info.tree):
                if isinstance(node, ast.Import):
                    for alias in node.names:
                        local = alias.asname or alias.name.split(".")[0]
                        target = (alias.name if alias.asname
                                  else alias.name.split(".")[0])
                        if target in modules:
                            self.names.setdefault(
                                (mod, local), ("module", target))
                        else:
                            # External module (numpy, json, ...): an
                            # attribute call on it must NOT fall back
                            # to method-name dispatch.
                            self.names.setdefault(
                                (mod, local), ("extmodule", target))
                elif isinstance(node, ast.ImportFrom):
                    target = self._from_target(node, pkg)
                    if target is None:
                        continue
                    for alias in node.names:
                        if alias.name == "*":
                            continue
                        local = alias.asname or alias.name
                        if f"{target}.{alias.name}" in self.module_bodies \
                                or (target == ""
                                    and alias.name in modules):
                            sub = (f"{target}.{alias.name}"
                                   if target else alias.name)
                            self.names.setdefault(
                                (mod, local), ("module", sub))
                        elif (target, alias.name) in self.names:
                            self.names.setdefault(
                                (mod, local),
                                self.names[(target, alias.name)])

    @staticmethod
    def _from_target(node: ast.ImportFrom, pkg: str):
        if node.level == 0:
            return node.module or ""
        parts = pkg.split(".") if pkg else []
        up = node.level - 1
        if up > len(parts):
            return None
        base = parts[: len(parts) - up] if up else parts
        if node.module:
            base = base + node.module.split(".")
        return ".".join(base)

    # -- call resolution --------------------------------------------

    def _resolve_edges(self) -> None:
        for fn in list(self.functions.values()):
            for node in _Scope.iter(fn.node):
                if isinstance(node, ast.Call):
                    targets = self.resolve_call(fn, node)
                    if len(targets) > 1:
                        fn.weak_calls.add(id(node))
                    fn.callees.append((node, targets))
                    for t in targets:
                        t.callers.append((fn, node))

    def resolve_call(self, fn: FuncNode, call: ast.Call) -> tuple:
        f = call.func
        mod = fn.module
        if isinstance(f, ast.Name):
            nested = self.functions.get(
                f"{fn.qual}.<locals>.{f.id}")
            if nested is not None:
                return (nested,)
            hit = self.names.get((mod, f.id))
            if hit is None:
                return ()
            (kind, qual) = hit
            if kind == "func":
                t = self.functions.get(qual)
                return (t,) if t else ()
            if kind == "class":
                cls = self.classes.get(qual)
                init = cls.methods.get("__init__") if cls else None
                return (init,) if init else ()
            return ()
        if not isinstance(f, ast.Attribute):
            return ()
        attr = f.attr
        base = f.value
        # module alias:  wire.frame(...)
        if isinstance(base, ast.Name):
            hit = self.names.get((mod, base.id))
            if hit is not None and hit[0] == "extmodule":
                return ()
            if hit is not None and hit[0] == "module":
                t = self.names.get((hit[1], attr))
                if t and t[0] == "func":
                    fnode = self.functions.get(t[1])
                    return (fnode,) if fnode else ()
                if t and t[0] == "class":
                    cls = self.classes.get(t[1])
                    init = (cls.methods.get("__init__")
                            if cls else None)
                    return (init,) if init else ()
                return ()
            if base.id in ("self", "cls") and fn.cls is not None:
                m = self._method_in(fn.cls, attr)
                if m is not None:
                    return (m,)
                return self._dispatch(attr)
        cls = self.receiver_class(fn, base)
        if isinstance(cls, ClassNode):
            m = self._method_in(cls, attr)
            if m is not None:
                return (m,)
        return self._dispatch(attr)

    def _method_in(self, cls: ClassNode, name: str):
        seen = set()
        stack = [cls]
        while stack:
            c = stack.pop()
            if c.qual in seen:
                continue
            seen.add(c.qual)
            if name in c.methods:
                return c.methods[name]
            for b in c.bases:
                bn = b.rsplit(".", 1)[-1]
                for cand in self.classes_by_name.get(bn, []):
                    stack.append(cand)
        return None

    def _dispatch(self, attr: str) -> tuple:
        if attr in NO_DISPATCH:
            return ()
        cands = self.methods_by_name.get(attr, [])
        if 0 < len(cands) <= DISPATCH_CAP:
            return tuple(cands)
        return ()

    def _local_ctors(self, fn: FuncNode) -> dict:
        """local name -> constructor name for this scope's single-Name
        assignments, built once per function (receiver_class is hot —
        the concurrency and evloop passes query it per access, and a
        rescan per query made the whole-program layer quadratic)."""
        cached = self._ctor_cache.get(fn.qual)
        if cached is None:
            cached = {}
            for node in _Scope.iter(fn.node):
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    ctor = self._ctor_name(node.value)
                    if ctor:
                        cached.setdefault(node.targets[0].id, ctor)
            self._ctor_cache[fn.qual] = cached
        return cached

    def receiver_class(self, fn: FuncNode, expr):
        """Best-effort class of a receiver expression: a local bound
        to a known constructor, or a `self.attr` the class's __init__
        typed.  Returns ClassNode, an external-ctor name string, or
        None."""
        if isinstance(expr, ast.Name):
            ctor = self._local_ctors(fn).get(expr.id)
            if ctor:
                known = self.classes_by_name.get(ctor)
                if known and len(known) == 1:
                    return known[0]
                return ctor
            return None
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self" and fn.cls is not None:
            return fn.cls.attr_classes.get(expr.attr)
        return None

    # -- thread roots -----------------------------------------------

    def _discover_threads(self) -> None:
        for fn in list(self.functions.values()):
            for (call, _t) in fn.callees:
                name = dotted(call.func)
                tail = name.rsplit(".", 1)[-1]
                if tail == "Thread":
                    self._thread_target(fn, call)
                elif tail.endswith(("HTTPServer", "TCPServer",
                                    "UDPServer")):
                    self._server_handlers(fn, call)

    def _thread_target(self, fn: FuncNode, call: ast.Call) -> None:
        target = None
        for kw in call.keywords:
            if kw.arg == "target":
                target = kw.value
        if target is None:
            return
        resolved = ()
        if isinstance(target, ast.Name):
            hit = self.names.get((fn.module, target.id))
            if hit and hit[0] == "func":
                t = self.functions.get(hit[1])
                resolved = (t,) if t else ()
        elif isinstance(target, ast.Attribute):
            base = target.value
            if isinstance(base, ast.Name) and base.id == "self" \
                    and fn.cls is not None:
                m = self._method_in(fn.cls, target.attr)
                resolved = (m,) if m else ()
            else:
                cls = self.receiver_class(fn, base)
                if isinstance(cls, ClassNode):
                    m = self._method_in(cls, target.attr)
                    resolved = (m,) if m else ()
                # A target on an external server object (e.g.
                # `self._httpd.serve_forever`): the serving work is
                # the handler class, found by _server_handlers.
        for t in resolved:
            self.thread_roots.setdefault(
                f"thread:{t.qual}", []).append(t)

    def _server_handlers(self, fn: FuncNode, call: ast.Call) -> None:
        """`ThreadingHTTPServer(addr, Handler)` — the handler class's
        entry methods run on server threads."""
        for arg in call.args[1:2]:
            if not isinstance(arg, ast.Name):
                continue
            hit = self.names.get((fn.module, arg.id))
            if not (hit and hit[0] == "class"):
                continue
            cls = self.classes.get(hit[1])
            if cls is None:
                continue
            group = f"thread:{cls.qual}"
            for (name, m) in cls.methods.items():
                if name.startswith(("do_", "handle")) \
                        or name == "log_message":
                    self.thread_roots.setdefault(group, []).append(m)

    # -- reachability -----------------------------------------------

    def _reach(self, seeds, strong_only: bool = False) -> set:
        seen = set()
        stack = [s.qual for s in seeds]
        while stack:
            q = stack.pop()
            if q in seen:
                continue
            seen.add(q)
            fn = self.functions.get(q)
            if fn is None:
                continue
            for (call, targets) in fn.callees:
                if strong_only and id(call) in fn.weak_calls:
                    continue
                for t in targets:
                    if t.qual not in seen:
                        stack.append(t.qual)
        return seen

    def _reachability(self) -> None:
        thread_fns = {t.qual for roots in self.thread_roots.values()
                      for t in roots}
        handler_classes = set()
        for (group, roots) in self.thread_roots.items():
            for t in roots:
                if t.cls is not None and group.endswith(t.cls.qual):
                    handler_classes.add(t.cls.qual)
        main_seeds = list(self.module_bodies.values())
        for fn in self.functions.values():
            if fn.is_module or fn.qual in thread_fns:
                continue
            if fn.cls is not None and fn.cls.qual in handler_classes:
                continue
            if not fn.callers:
                main_seeds.append(fn)   # API entry: only tests/main
                #                         call it -> main thread
        groups = {"main": self._reach(main_seeds)}
        # Thread-side reachability follows only STRONG edges: a
        # multi-candidate name dispatch from a handler would otherwise
        # pull half the program onto the server thread.
        for (group, roots) in self.thread_roots.items():
            groups[group] = self._reach(roots, strong_only=True)
        self.roots_of = {}
        for (group, quals) in groups.items():
            for q in quals:
                self.roots_of.setdefault(q, set()).add(group)

    def root_groups(self, fn: FuncNode) -> set:
        return self.roots_of.get(fn.qual, set())

    # -- locks -------------------------------------------------------

    def find_locks(self) -> None:
        """Module-global locks (NAME = threading.Lock())."""
        for (mod, body) in self.module_bodies.items():
            for node in body.node.body:
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    ctor = self._ctor_name(node.value)
                    if ctor in _LOCK_CTORS:
                        self.lock_ids.add(
                            ("global", mod, node.targets[0].id))

    def lock_id_of(self, fn: FuncNode, expr):
        """The lock identity a `with <expr>:` guards, or None."""
        if isinstance(expr, ast.Name):
            lid = ("global", fn.module, expr.id)
            return lid if lid in self.lock_ids else None
        if isinstance(expr, ast.Attribute):
            base = expr.value
            if isinstance(base, ast.Name) and base.id == "self" \
                    and fn.cls is not None \
                    and expr.attr in fn.cls.lock_attrs:
                return ("attr", fn.cls.qual, expr.attr)
            cls = self.receiver_class(fn, base)
            if isinstance(cls, ClassNode) \
                    and expr.attr in cls.lock_attrs:
                return ("attr", cls.qual, expr.attr)
            # Unknown receiver but the attr is SOME class's lock:
            # resolve only when unambiguous across the program.
            owners = [c for c in self.classes.values()
                      if expr.attr in c.lock_attrs]
            if len(owners) == 1:
                return ("attr", owners[0].qual, expr.attr)
        return None

    def with_regions(self, fn: FuncNode) -> list:
        """(lock id, With node) for every lock-guarded region of this
        scope (cached — the lock fixpoint and the concurrency pass
        query it per statement)."""
        cached = self._regions_cache.get(fn.qual)
        if cached is not None:
            return cached
        out = []
        for node in _Scope.iter(fn.node):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    lid = self.lock_id_of(fn, item.context_expr)
                    if lid is not None:
                        out.append((lid, node))
        self._regions_cache[fn.qual] = out
        return out

    def locks_held_at(self, fn: FuncNode, node) -> set:
        """Locks held at `node`: enclosing with-regions plus the
        function's inherited entry locks."""
        held = set(self.entry_locks.get(fn.qual, frozenset()))
        line = getattr(node, "lineno", None)
        if line is None:
            return held
        for (lid, region) in self.with_regions(fn):
            if region.lineno <= line <= getattr(
                    region, "end_lineno", region.lineno):
                held.add(lid)
        return held

    def _lock_fixpoint(self) -> None:
        """Must-analysis: a function whose EVERY analyzed call site
        runs under lock L holds L on entry.  Entries (module bodies,
        thread roots, API entry points) start at the empty set;
        everything else starts at the universe and intersects down."""
        self.find_locks()
        universe = frozenset(self.lock_ids)
        self.entry_locks = {}
        for fn in self.functions.values():
            entry = fn.is_module or not fn.callers
            self.entry_locks[fn.qual] = (frozenset() if entry
                                         else universe)
        for t in (r for roots in self.thread_roots.values()
                  for r in roots):
            self.entry_locks[t.qual] = frozenset()
        for _ in range(12):
            changed = False
            for fn in self.functions.values():
                if not fn.callers or fn.is_module:
                    continue
                acc = None
                for (caller, call) in fn.callers:
                    held = frozenset(
                        self.locks_held_at(caller, call))
                    acc = held if acc is None else (acc & held)
                acc = acc if acc is not None else frozenset()
                if acc != self.entry_locks[fn.qual]:
                    self.entry_locks[fn.qual] = acc
                    changed = True
            if not changed:
                break
