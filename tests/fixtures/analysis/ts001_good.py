"""Known-good: branches on static shape data only (TS001)."""

import jax
import jax.numpy as jnp


def relu_sum(x: jax.Array) -> jax.Array:
    if x.shape[0] > 1:
        return jnp.sum(jnp.maximum(x, 0))
    return jnp.maximum(x, 0)


def maybe(x: jax.Array, y=None) -> jax.Array:
    if y is None:
        return x
    return jnp.where(x > 0, x, y)
