"""Known-good: bounded buffers and a shed policy (RB004)."""

import collections
import queue

MAX_BUFFERED = 4096


def make_buffers():
    uploads = queue.Queue(maxsize=MAX_BUFFERED)
    pages = collections.deque(maxlen=64)
    return (uploads, pages)


def ingest_forever(source, buffered, counters):
    while True:
        blob = source.take()
        if len(buffered) >= MAX_BUFFERED:
            counters["shed"] += 1      # reject-newest, counted
            continue
        buffered.append(blob)
