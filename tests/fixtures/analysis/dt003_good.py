"""Known-good: literals and shifts fit the dtype (DT003)."""

import jax.numpy as jnp


def in_range():
    x = jnp.zeros((4,), jnp.uint8)
    y = jnp.zeros((4,), jnp.uint32)
    return (x & 0xFE, y >> 16)
