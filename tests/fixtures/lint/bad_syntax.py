"""Known-bad: does not parse (lint check 1)."""


def broken(:
    return 0
