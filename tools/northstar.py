"""North-star-scale heavy hitters: stream a large report batch through
the chunked incremental runner end to end.

This is the flagship workload (reference driver semantics,
/root/reference/poc/examples.py:37-91 for Count and :94-170 for the
weighted Sum mode, scaled up): device-batched client sharding ->
HostReportStore -> chunked incremental rounds with per-chunk metrics
and memory accounting.  Run it on the chip for the real number, or on
CPU (JAX_PLATFORMS=cpu) as the memory-accounted simulation — the
execution model and the compiled programs are identical either way;
only the rate changes (the JSON's "platform" field says which one
produced it).

Planted heavy hitters are full-width bit paths; when two or more are
planted, the second shares a long prefix with the first (diverging at
3/4 of the tree depth), so the frontier stays >1 wide deep into the
tree — the shape that exercises the shared-ancestor carry layout at
depth.

Prints one JSON line:
  {"inst": "count"|"sum", "platform": ..., "reports": N, "bits": B,
   "chunk_size": C, "levels": B, "wall_seconds": ...,
   "node_evals_total": ..., "node_evals_per_sec": ...,
   "per_chunk_evals_per_sec_p50": ..., "memory": {...},
   "envelope": {...}, "heavy_hitters": [...so many...], "ok": true}

Examples (each shape has a recorded ok=true run, see NORTHSTAR_r05*):
  JAX_PLATFORMS=cpu python tools/northstar.py --reports 8192 --bits 256
      # full north-star depth, chunked; ~83 min on a 1-core CPU host
      # (per-level cost grows with depth - the binder hashes the
      # carried tree - so 20k reports at 256 bits is ~6 h there)
  JAX_PLATFORMS=cpu python tools/northstar.py --inst sum --reports 10000 \\
      --bits 32 --max-weight 255
  python tools/northstar.py --resident --reports 10000 --bits 256
      # device-resident carries: the fast path whenever the carry fits
      # one chip's HBM, and the only fast path on a tunnel-attached
      # chip (chunked mode is transfer-bound there: it moves the full
      # carry host<->device every level); 256 levels in ~13 min on a
      # v5-lite chip
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


# -- checkpoint container ---------------------------------------------
#
# The run state blob (HeavyHittersRun.to_bytes) only binds the verify
# key / ctx / thresholds and tree shape; the synthetic reports are
# rebuilt from CLI args, so a resume with a different --seed /
# --planted / --inst silently continues carried state over mismatched
# reports and only surfaces as ok=false after the full remaining wall
# time (ADVICE r5).  The checkpoint therefore stamps every parameter
# the report rebuild depends on into its header, and --resume verifies
# them before touching the run state.

SHARD_PARAM_KEYS = ("inst", "reports", "bits", "seed", "planted",
                    "max_weight", "tail_weight")


def shard_params(args) -> dict:
    """The CLI parameters the synthetic report batch is a pure
    function of (plant_paths + weight assignment + shard RNG)."""
    return {k: getattr(args, k) for k in SHARD_PARAM_KEYS}


def write_checkpoint_bytes(vk: bytes, params: dict,
                           blob: bytes) -> bytes:
    """vk-length | vk | params-length | params-json | run blob."""
    header = json.dumps(params, sort_keys=True).encode()
    return (len(vk).to_bytes(2, "little") + vk
            + len(header).to_bytes(4, "little") + header + blob)


def read_checkpoint_bytes(raw: bytes) -> tuple:
    """Inverse of write_checkpoint_bytes -> (vk, params, blob)."""
    klen = int.from_bytes(raw[:2], "little")
    vk = raw[2:2 + klen]
    off = 2 + klen
    plen = int.from_bytes(raw[off:off + 4], "little")
    try:
        params = json.loads(raw[off + 4:off + 4 + plen])
    except ValueError:
        raise ValueError(
            "checkpoint has no shard-parameter header (written by an "
            "older tools/northstar.py) — re-run without --resume")
    return (vk, params, raw[off + 4 + plen:])


def verify_shard_params(saved: dict, current: dict) -> list:
    """Mismatched parameter names (resume must refuse on any)."""
    return sorted(k for k in set(saved) | set(current)
                  if saved.get(k) != current.get(k))


def plant_paths(rng, planted: int, bits: int):
    """Full-width planted heavy-hitter paths, (planted, bits) bool.

    Rows are pairwise distinct; when >= 2 are planted, row 1 copies
    row 0's first 3/4 of the tree and diverges exactly there, so the
    two survivors ride one shared ancestor chain for 3/4 of the run.
    """
    import numpy as np

    if planted > 2 ** bits:
        raise ValueError(
            f"cannot plant {planted} distinct paths in a "
            f"{bits}-bit tree ({2 ** bits} exist)")
    paths = rng.integers(0, 2, (planted, bits)).astype(bool)
    if planted >= 2:
        split = max(1, (3 * bits) // 4)
        if split >= bits:
            split = bits - 1
        paths[1, :split] = paths[0, :split]
        paths[1, split] = ~paths[0, split]
        paths[1, split + 1:] = rng.integers(
            0, 2, bits - split - 1).astype(bool)
    for r in range(planted):
        while any(np.array_equal(paths[r], paths[s]) for s in range(r)):
            paths[r] = rng.integers(0, 2, bits).astype(bool)
    return paths


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--inst", choices=("count", "sum"),
                        default="count")
    parser.add_argument("--reports", type=int, default=100_000)
    parser.add_argument("--bits", type=int, default=64)
    parser.add_argument("--chunk-size", type=int, default=4096)
    parser.add_argument("--planted", type=int, default=3,
                        help="number of heavy-hitter values planted")
    parser.add_argument("--max-weight", type=int, default=7,
                        help="MasticSum max_measurement; planted "
                             "reports carry this weight (sum mode)")
    parser.add_argument("--tail-weight", type=int, default=1,
                        help="weight of the uniform-tail reports "
                             "(sum mode)")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--resident", action="store_true",
                        help="keep carries device-resident for the "
                             "whole run instead of streaming host "
                             "chunks — the fast path whenever the "
                             "full carry fits one chip's HBM (and the "
                             "only fast path when the chip is reached "
                             "over a network tunnel: chunked mode "
                             "moves the full carry host<->device "
                             "every level)")
    parser.add_argument("--mesh", type=int, default=0,
                        help="shard the chunk's report axis over this "
                             "many devices (virtual CPU devices when "
                             "the platform is cpu)")
    parser.add_argument("--out", type=str, default=None,
                        help="also write the JSON artifact here")
    parser.add_argument("--checkpoint", type=str, default=None,
                        help="write the run state here every "
                             "--checkpoint-every levels (verify key "
                             "+ HeavyHittersRun.to_bytes, atomic "
                             "rename); with --resume, restore from "
                             "it and continue")
    parser.add_argument("--checkpoint-every", type=int, default=16)
    parser.add_argument("--resume", action="store_true",
                        help="resume from --checkpoint instead of "
                             "starting fresh (reports are rebuilt "
                             "deterministically from --seed, so only "
                             "the run state needs the file)")
    args = parser.parse_args()

    if args.checkpoint_every < 1:
        # A value of 0 used to crash with ZeroDivisionError at
        # `run.level % args.checkpoint_every` — after the first
        # (possibly long) level completed (ADVICE r5).
        parser.error(f"--checkpoint-every must be >= 1 "
                     f"(got {args.checkpoint_every})")

    # Read and verify the checkpoint BEFORE the jax import and the
    # multi-minute shard phase: a mismatched resume fails in
    # milliseconds, not after the full remaining wall time (ADVICE
    # r5 — the run state blob binds vk/ctx/thresholds but the
    # synthetic reports are rebuilt from these CLI args).
    resumed_from = None
    ckpt_blob = None
    vk = None
    if args.resume:
        if not args.checkpoint:
            parser.error("--resume needs --checkpoint PATH")
        with open(args.checkpoint, "rb") as f:
            raw = f.read()
        (vk, saved_params, ckpt_blob) = read_checkpoint_bytes(raw)
        mismatched = verify_shard_params(saved_params,
                                         shard_params(args))
        if mismatched:
            detail = ", ".join(
                f"{k}: checkpoint={saved_params.get(k)!r} "
                f"vs run={getattr(args, k, None)!r}"
                for k in mismatched)
            print(f"--resume refused: the checkpoint was written for "
                  f"different shard parameters ({detail}); the "
                  f"rebuilt reports would not match the carried "
                  f"state and the run would only fail at the end",
                  file=sys.stderr)
            sys.exit(2)

    if args.mesh:
        # Chunked mode shards ANY chunk_size: the runner pads each
        # chunk's device rows to the shard multiple and masks the dead
        # lanes (drivers/chunked.ChunkedIncrementalRunner._device_rows)
        # — the old parse-time divisibility refusal is gone.  Resident
        # mode's batch IS the device tile, so it still must divide;
        # fail before the multi-minute shard phase, not after it.
        if args.resident and args.reports % args.mesh:
            parser.error(
                f"--reports {args.reports} must be divisible by "
                f"--mesh {args.mesh} in --resident mode (the resident "
                f"batch shards without padding; chunked mode pads)")
        # Virtual device count must be pinned before jax import.
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{args.mesh}").strip()

    t_start = time.time()

    def stamp(msg: str) -> None:
        print(f"[northstar {time.time() - t_start:8.1f}s] {msg}",
              file=sys.stderr, flush=True)

    import numpy as np
    import jax
    import jax.numpy as jnp

    requested = os.environ.get("JAX_PLATFORMS", "").strip()
    if requested and "axon" not in requested.split(","):
        jax.config.update("jax_platforms", requested)

    from mastic_tpu import MasticCount, MasticSum
    from mastic_tpu.backend.mastic_jax import BatchedMastic
    from mastic_tpu.common import gen_rand
    from mastic_tpu.drivers.chunked import HostReportStore, memory_envelope
    from mastic_tpu.drivers.heavy_hitters import HeavyHittersRun

    (R, bits, C) = (args.reports, args.bits, args.chunk_size)
    if args.inst == "sum":
        m = MasticSum(bits, args.max_weight)
    else:
        m = MasticCount(bits)
    bm = BatchedMastic(m)
    rng = np.random.default_rng(args.seed)
    platform = jax.devices()[0].platform
    # Persistent XLA compile cache: a proven win on chip, but on the
    # CPU fabric RELOADING cached executables is unsound — the second
    # process on a warm cache segfaults or, worse, loads a silently
    # wrong program that rejects every report (r9 measured this at
    # the pre-pipeline HEAD too, so it is a fabric landmine, not a
    # pipeline regression; PERF.md §7).  The wiring is therefore
    # platform-gated; MASTIC_COMPILE_CACHE=1 forces it on anywhere,
    # =0 forces it off anywhere.
    cache_lever = os.environ.get("MASTIC_COMPILE_CACHE", "")
    if cache_lever == "1" or (cache_lever != "0"
                              and platform != "cpu"):
        jax.config.update("jax_compilation_cache_dir",
                          "/tmp/mastic_tpu_jax_cache")
        jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                          -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.0)
    if args.mesh and args.mesh > jax.device_count():
        print(f"--mesh {args.mesh} exceeds the {jax.device_count()} "
              f"available {platform} device(s)", file=sys.stderr)
        sys.exit(2)
    stamp(f"device={platform} inst={args.inst} reports={R} bits={bits} "
          f"chunk={C}")

    # Plant a few heavy paths (one pair colliding on a long prefix);
    # the rest is a uniform tail that the threshold prunes early.
    paths = plant_paths(rng, args.planted, bits)
    share_heavy = 0.6
    heavy_rows = int(R * share_heavy)
    choice = rng.integers(0, args.planted, heavy_rows)
    alphas = np.concatenate([
        paths[choice],
        rng.integers(0, 2, (R - heavy_rows, bits)).astype(bool)])

    # Per-report weights: heavy reports carry max weight, the tail
    # carries tail weight (Count: everyone weighs 1; the threshold is
    # in aggregate-weight units either way, reference examples.py:135).
    if args.inst == "sum":
        (w_heavy, w_tail) = (args.max_weight, args.tail_weight)
    else:
        (w_heavy, w_tail) = (1, 1)
    weights = np.concatenate([
        np.full(heavy_rows, w_heavy, np.int64),
        np.full(R - heavy_rows, w_tail, np.int64)])
    threshold = int(heavy_rows / args.planted * w_heavy * 0.5)

    def beta_limbs(weight: int) -> np.ndarray:
        beta = [m.field(1)] + m.flp.encode(int(weight))
        return np.stack([bm.spec.int_to_limbs(el.int()) for el in beta])

    beta_table = {int(w): beta_limbs(int(w))
                  for w in np.unique(weights)}
    betas = np.stack([beta_table[int(w)] for w in (w_heavy, w_tail)])
    beta_idx = (weights != w_heavy).astype(np.int64)  # 0=heavy, 1=tail

    # Device-batched client sharding, chunk by chunk, directly into
    # the host store (the client fleet axis; scalar clients would take
    # ~R seconds at 256 bits).
    stamp("shard: compiling client program")
    shard_fn = jax.jit(
        lambda a, b, n, r: bm.shard_device(b"northstar", a, b, n, r))
    num_chunks = -(-R // C)
    arrays = None
    chunk_batches = []
    shard_t0 = time.time()
    for i in range(num_chunks):
        (lo, hi) = (i * C, min((i + 1) * C, R))
        idx = np.arange(lo, hi)
        if hi - lo < C:  # pad the tail chunk (same compiled program)
            idx = np.concatenate([idx, np.full(C - (hi - lo), lo)])
        a = jnp.asarray(alphas[idx])
        b = jnp.asarray(betas[beta_idx[idx]])
        n = jnp.asarray(rng.integers(0, 256, (C, 16), dtype=np.uint8))
        r = jnp.asarray(rng.integers(0, 256, (C, m.RAND_SIZE),
                                     dtype=np.uint8))
        (batch, ok) = shard_fn(a, b, n, r)
        assert bool(np.all(np.asarray(ok))), \
            "XOF rejection fired during synthetic shard (p ~ 2^-32)"
        if args.resident:
            # Keep the (tail-trimmed) device arrays; no host store.
            chunk_batches.append(jax.tree_util.tree_map(
                lambda x: x[:hi - lo], batch))
            if i == 0:
                stamp(f"shard: chunk 0 done "
                      f"({time.time() - shard_t0:.1f}s incl compile)")
            continue
        chunk_store = HostReportStore.from_batch(batch, C)
        if arrays is None:
            arrays = {
                k: (np.zeros((R,) + v.shape[1:], v.dtype)
                    if isinstance(v, np.ndarray) else
                    tuple(np.zeros((R,) + p.shape[1:], p.dtype)
                          if isinstance(p, np.ndarray) else None
                          for p in v) if isinstance(v, tuple) else None)
                for (k, v) in chunk_store.arrays.items()}
        for (k, v) in chunk_store.arrays.items():
            if isinstance(v, np.ndarray):
                arrays[k][lo:hi] = v[:hi - lo]
            elif isinstance(v, tuple):
                for (dst, src) in zip(arrays[k], v):
                    if isinstance(src, np.ndarray):
                        dst[lo:hi] = src[:hi - lo]
        if i == 0:
            stamp(f"shard: chunk 0 done ({time.time() - shard_t0:.1f}s "
                  "incl compile)")
    shard_wall = time.time() - shard_t0
    stamp(f"shard: {R} reports in {shard_wall:.1f}s "
          f"({R / shard_wall:.0f} reports/s)")

    mesh = None
    if args.mesh:
        from mastic_tpu.parallel import make_mesh
        mesh = make_mesh(args.mesh, nodes_axis=1)
        stamp(f"mesh: report axis sharded over {args.mesh} devices")

    # Checkpoint file = vk + shard-parameter header + HeavyHittersRun
    # blob (write_checkpoint_bytes, read + verified at parse time
    # above).  The vk rides along because the blob's binding digest
    # pins it (a fresh key would silently reject every carried
    # report); the header pins the report rebuild.
    if vk is None:
        vk = gen_rand(m.VERIFY_KEY_SIZE)

    thresholds = {"default": threshold}
    if args.resident:
        full_batch = jax.tree_util.tree_map(
            lambda *xs: jnp.concatenate(xs, axis=0), *chunk_batches)
        chunk_batches.clear()  # don't hold 2x the batch in HBM
        if ckpt_blob is not None:
            run = HeavyHittersRun.from_bytes(
                m, b"northstar", thresholds, None, vk, ckpt_blob,
                batch=full_batch, mesh=mesh)
        else:
            run = HeavyHittersRun(m, b"northstar", thresholds,
                                  None, verify_key=vk,
                                  batch=full_batch, mesh=mesh)
    else:
        store = HostReportStore(arrays, R, C)
        if ckpt_blob is not None:
            run = HeavyHittersRun.from_bytes(
                m, b"northstar", thresholds, None, vk, ckpt_blob,
                store=store, mesh=mesh)
        else:
            run = HeavyHittersRun(m, b"northstar", thresholds,
                                  None, verify_key=vk, store=store,
                                  mesh=mesh)
    if ckpt_blob is not None:
        resumed_from = run.level
        stamp(f"resumed from checkpoint at level {run.level}")

    def save_checkpoint() -> None:
        tmp = args.checkpoint + ".tmp"
        with open(tmp, "wb") as f:
            f.write(write_checkpoint_bytes(vk, shard_params(args),
                                           run.to_bytes()))
        os.replace(tmp, args.checkpoint)

    stamp(f"rounds: threshold={threshold} planted={args.planted}")
    # The run's round/chunk spans nest under one "collection" span —
    # the same span schema tools/serve.py's epochs emit, so an offline
    # northstar trace and a live service trace diff directly
    # (MASTIC_TRACE_FILE=path captures both as JSONL).
    from mastic_tpu.obs import trace as obs_trace
    coll_span = obs_trace.get_tracer().start_detached_span(
        "collection", tool="northstar", inst=args.inst,
        reports=R, bits=bits,
        mode="resident" if args.resident else "chunked")
    agg_t0 = time.time()
    evals_total = 0
    chunk_rates: list = []
    level = 0
    more = True
    while more:
        # The deepest level's round runs inside the step() call that
        # returns False — consume metrics appended since the last
        # iteration, not just on True returns, or the final level's
        # evals vanish from the totals.
        with obs_trace.get_tracer().use_parent(coll_span):
            more = run.step()
        if args.checkpoint and more \
                and run.level % args.checkpoint_every == 0:
            save_checkpoint()
            stamp(f"checkpoint written at level {run.level}")
        for mx in run.metrics[level:]:
            evals_total += mx.node_evals
            if "chunks" in mx.extra:
                rates = [c["node_evals_per_sec"]
                         for c in mx.extra["chunks"]]
            else:  # resident: one device round, rate from its wall
                wall_ms = mx.extra.get("round_wall_ms", 0.0)
                rates = ([mx.node_evals / (wall_ms / 1e3)]
                         if wall_ms else [])
            chunk_rates += rates
            if level % 8 == 0 or level == bits - 1 or not more:
                p50 = (sorted(rates)[len(rates) // 2]
                       if rates else 0.0)
                stamp(f"level {mx.level}: frontier={mx.frontier_width}"
                      f" accepted={mx.accepted}/{mx.reports_total} "
                      f"evals/s p50={p50:.0f}")
            level += 1
    agg_wall = time.time() - agg_t0
    obs_trace.get_tracer().end_span(coll_span)

    hitters = run.result()
    expected = {tuple(bool(b) for b in row) for row in paths}
    got = set(hitters)
    mem = run.runner.memory_accounting()
    # Pipelined-executor summary (drivers/pipeline.py): overlap
    # efficiency is a measured number in the artifact, and a
    # degrade-to-serial fallback is named, never silent.
    pipe_rounds = [mx.extra["pipeline"] for mx in run.metrics
                   if "pipeline" in mx.extra]
    pipeline_out = None
    if pipe_rounds:
        effs = sorted(p["overlap_efficiency"] for p in pipe_rounds)
        pipeline_out = {
            "mode": pipe_rounds[-1]["mode"],
            "rounds_pipelined": sum(
                p["mode"] == "pipelined" for p in pipe_rounds),
            "rounds_total": len(pipe_rounds),
            "overlap_efficiency_p50": effs[len(effs) // 2],
            "compile_inline_ms_total": round(
                sum(p["compile_inline_ms"] for p in pipe_rounds), 1),
            "fallbacks": sorted({p["fallback"] for p in pipe_rounds
                                 if p["fallback"]}),
        }
    # Mesh summary (drivers/chunked.py stamps extra["mesh"]): psum
    # bytes and shard skew per round, so the collective overhead at
    # scale is a recorded number, not an inference.
    mesh_rounds = [mx.extra["mesh"] for mx in run.metrics
                   if "mesh" in mx.extra]
    mesh_out = None
    if mesh_rounds:
        skews = sorted(mr["shard_wait_skew_ms_max"]
                       for mr in mesh_rounds)
        mesh_out = {
            "report_shards": mesh_rounds[-1]["report_shards"],
            "device_rows_per_chunk":
                mesh_rounds[-1]["device_rows_per_chunk"],
            "rows_per_shard": mesh_rounds[-1]["rows_per_shard"],
            "psum_bytes_total": sum(mr["psum_bytes_per_round"]
                                    for mr in mesh_rounds),
            "psum_bytes_per_round_last":
                mesh_rounds[-1]["psum_bytes_per_round"],
            "shard_wait_skew_ms_p50": skews[len(skews) // 2],
            "shard_wait_skew_ms_max": skews[-1],
        }
    # Envelope at the FINAL width — a frontier that forced _grow must
    # be reflected next to the measured accounting.  Resident mode's
    # "chunk" is the entire batch.
    envelope = memory_envelope(bm, R if args.resident else C,
                               run.runner.width, R,
                               n_device_shards=args.mesh or 1)
    p50 = (sorted(chunk_rates)[len(chunk_rates) // 2]
           if chunk_rates else 0.0)
    out = {
        "inst": args.inst, "platform": platform,
        "mode": "resident" if args.resident else "chunked",
        "mesh_devices": args.mesh or 1,
        "reports": R, "bits": bits,
        "chunk_size": 0 if args.resident else C,
        "levels": len(run.metrics),
        "threshold": threshold,
        "shard_seconds": round(shard_wall, 1),
        "wall_seconds": round(agg_wall, 1),
        "node_evals_total": evals_total,
        "node_evals_per_sec": round(evals_total / agg_wall, 1),
        "per_chunk_evals_per_sec_p50": round(p50, 1),
        # Per-shard twin of the p50 (live rate / report shards): the
        # number to hold against the single-chip roofline (PERF.md §8).
        "per_chunk_evals_per_sec_per_shard_p50": round(
            p50 / (args.mesh or 1), 1),
        "memory": mem,
        "envelope": envelope,
        "heavy_hitters_found": len(hitters),
        "heavy_hitters_expected": len(expected),
        # Tracer state: how many spans this run emitted, where the
        # JSONL (if any) went — so an artifact names its own trace.
        "obs": obs_trace.get_tracer().snapshot(),
        "ok": got == expected,
    }
    if pipeline_out is not None:
        out["pipeline"] = pipeline_out
    if mesh_out is not None:
        out["mesh"] = mesh_out
    if args.inst == "sum":
        out["max_weight"] = args.max_weight
    if resumed_from is not None:
        # wall/evals cover only this process's rounds.
        out["resumed_from_level"] = resumed_from
    line = json.dumps(out)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    if not out["ok"]:
        sys.exit(1)


if __name__ == "__main__":
    main()
