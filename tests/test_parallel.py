"""Multi-chip sharding tests on the virtual 8-device CPU mesh.

The sharded round must produce bit-identical results to the unsharded
batched backend (collectives must not change the math).
"""

import pytest

pytestmark = pytest.mark.slow

import numpy as np
import jax
import jax.numpy as jnp

from mastic_tpu import MasticCount
from mastic_tpu.backend.mastic_jax import BatchedMastic
from mastic_tpu.parallel import (install_grid_sharding, make_mesh,
                                 shard_batch, sharded_gen_fn,
                                 sharded_round_fn)

CTX = b"mesh test"
VK = bytes(range(32))


def _reports(mastic, values, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for v in values:
        alpha = mastic.vidpf.test_index_from_int(v, mastic.vidpf.BITS)
        nonce = rng.integers(0, 256, 16, dtype=np.uint8).tobytes()
        rand = rng.integers(0, 256, mastic.RAND_SIZE,
                            dtype=np.uint8).tobytes()
        out.append((nonce,) + mastic.shard(CTX, (alpha, 1), nonce, rand))
    return out


def test_sharded_round_matches_unsharded():
    assert len(jax.devices()) == 8
    mastic = MasticCount(3)
    bm = BatchedMastic(mastic)
    values = [0b101, 0b100, 0b101, 0b001, 0b101, 0b100, 0b110, 0b000]
    reports = _reports(mastic, values)
    level = 1
    prefixes = tuple(mastic.vidpf.test_index_from_int(v, 2)
                     for v in range(4))
    agg_param = (level, prefixes, False)

    nonces = np.stack([np.frombuffer(n, np.uint8)
                       for (n, _, _) in reports])
    cws = bm.vidpf.cws_from_host([ps for (_, ps, _) in reports])
    keys = [np.stack([np.frombuffer(sh[a][0], np.uint8)
                      for (_, _, sh) in reports]) for a in range(2)]

    # Unsharded baseline.
    base_fn = jax.jit(
        lambda n, c, k0, k1: _round(bm, agg_param, n, c, k0, k1))
    base = base_fn(jnp.asarray(nonces), cws, jnp.asarray(keys[0]),
                   jnp.asarray(keys[1]))

    # Sharded across a (4 reports x 2 nodes) mesh.
    from mastic_tpu.backend.mastic_jax import ReportBatch
    mesh = make_mesh(8, nodes_axis=2)
    batch = ReportBatch(
        nonces=shard_batch(mesh, jnp.asarray(nonces)),
        cws=jax.tree.map(lambda x: shard_batch(mesh, x), cws),
        keys=shard_batch(mesh, jnp.asarray(np.stack(keys, axis=1))),
        leader_proofs=None, helper_seeds=None, leader_seeds=None,
        peer_parts=(None, None))
    install_grid_sharding(bm, mesh)
    try:
        fn = sharded_round_fn(bm, mesh, VK, CTX, agg_param)
        sharded = fn(batch)
    finally:
        bm.vidpf.constrain_state = None

    (agg0, agg1, accept, ok) = sharded
    assert bool(np.all(np.asarray(accept)))
    assert bool(np.all(np.asarray(ok)))
    np.testing.assert_array_equal(np.asarray(agg0), np.asarray(base[0]))
    np.testing.assert_array_equal(np.asarray(agg1), np.asarray(base[1]))

    result = mastic.unshard(
        agg_param,
        [bm.agg_share_to_host(agg0), bm.agg_share_to_host(agg1)],
        len(reports))
    assert result == [sum(1 for v in values if v >> 1 == p)
                      for p in range(4)]


def test_sharded_weight_check_round():
    """The fused sharded round must also cover weight-check rounds
    (device FLP query + decide under pjit)."""
    mastic = MasticCount(3)
    bm = BatchedMastic(mastic)
    values = [0b101, 0b100, 0b101, 0b001, 0b101, 0b100, 0b110, 0b000]
    reports = _reports(mastic, values, seed=7)
    batch = bm.marshal_reports(reports)
    agg_param = (0, ((False,), (True,)), True)

    mesh = make_mesh(8, nodes_axis=2)
    batch = jax.tree.map(lambda x: shard_batch(mesh, x), batch)
    install_grid_sharding(bm, mesh)
    try:
        fn = sharded_round_fn(bm, mesh, VK, CTX, agg_param)
        (agg0, agg1, accept, ok) = fn(batch)
    finally:
        bm.vidpf.constrain_state = None
    assert bool(np.all(np.asarray(accept)))
    assert bool(np.all(np.asarray(ok)))
    result = mastic.unshard(
        agg_param,
        [bm.agg_share_to_host(agg0), bm.agg_share_to_host(agg1)],
        len(reports))
    assert result == [sum(1 for v in values if v >> 2 == p)
                      for p in range(2)]


@pytest.mark.parametrize("chunked", [False, True],
                         ids=["resident", "chunked"])
def test_incremental_heavy_hitters_sharded(chunked):
    """The production execution model (incremental engine) over the
    mesh: a full multi-level heavy-hitters run with report-sharded
    carries must be bit-identical to the single-device run — the claim
    PERF.md's 8-chip projection rests on."""
    from mastic_tpu.common import gen_rand
    from mastic_tpu.drivers.heavy_hitters import (
        HeavyHittersRun, get_reports_from_measurements)

    mastic = MasticCount(3)
    meas = [((bool(v >> 2 & 1), bool(v >> 1 & 1), bool(v & 1)), True)
            for v in [0, 0, 0, 5, 5, 5, 3, 1,
                      0, 5, 6, 6, 0, 5, 2, 7]]
    reports = get_reports_from_measurements(mastic, CTX, meas)
    # Tamper one report: the reject verdict must also match across
    # the sharded/unsharded pair.
    (nonce, ps, shares) = reports[6]
    (key, proof, seed, part) = shares[0]
    reports[6] = (nonce, ps, [
        (bytes([key[0] ^ 1]) + key[1:], proof, seed, part), shares[1]])
    vk = gen_rand(mastic.VERIFY_KEY_SIZE)
    thresholds = {"default": 3}
    mesh = make_mesh(8, nodes_axis=1)
    # The chunk is the device tile: it must shard evenly (16 reports
    # -> two chunks of 8 over the 8-device reports axis).
    kwargs = {"chunk_size": 8} if chunked else {}

    base = HeavyHittersRun(mastic, CTX, thresholds, reports,
                           verify_key=vk, **kwargs)
    meshed = HeavyHittersRun(mastic, CTX, thresholds, reports,
                             verify_key=vk, mesh=mesh, **kwargs)
    assert meshed.runner.mesh is mesh
    while True:
        (a, b) = (base.step(), meshed.step())
        assert a == b
        (m0, m1) = (base.metrics[-1], meshed.metrics[-1])
        assert m0.accepted == m1.accepted
        assert m0.rejected_eval_proof == m1.rejected_eval_proof
        if not a:
            break
    assert base.result() == meshed.result()
    assert base.result()  # honest hitters survive


def _round(bm, agg_param, nonces, cws, k0, k1):
    p0 = bm.prep(0, VK, CTX, agg_param, nonces, cws, k0)
    p1 = bm.prep(1, VK, CTX, agg_param, nonces, cws, k1)
    accept = jnp.all(p0.eval_proof == p1.eval_proof, axis=-1)
    return (bm.aggregate(p0.out_share, accept),
            bm.aggregate(p1.out_share, accept))


def test_sharded_gen_matches_unsharded():
    mastic = MasticCount(2)
    bm = BatchedMastic(mastic)
    mesh = make_mesh(8, nodes_axis=1)
    rng = np.random.default_rng(5)
    num = 8
    alphas = rng.integers(0, 2, (num, 2)).astype(bool)
    betas = np.stack([
        np.stack([bm.spec.int_to_limbs(1), bm.spec.int_to_limbs(1)])
        for _ in range(num)
    ])
    nonces = rng.integers(0, 256, (num, 16), dtype=np.uint8)
    rand = rng.integers(0, 256, (num, 32), dtype=np.uint8)

    (cws_ref, keys_ref, ok_ref) = bm.vidpf.gen(
        jnp.asarray(alphas), jnp.asarray(betas), CTX,
        jnp.asarray(nonces), jnp.asarray(rand))

    fn = sharded_gen_fn(bm, mesh, CTX)
    (cws, keys, ok) = fn(
        shard_batch(mesh, jnp.asarray(alphas)),
        shard_batch(mesh, jnp.asarray(betas)),
        shard_batch(mesh, jnp.asarray(nonces)),
        shard_batch(mesh, jnp.asarray(rand)))

    assert bool(np.all(np.asarray(ok))) == bool(np.all(np.asarray(ok_ref)))
    np.testing.assert_array_equal(np.asarray(keys), np.asarray(keys_ref))
    for (got, want) in zip(cws, cws_ref):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
