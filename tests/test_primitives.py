"""Unit tests for the crypto/field substrate, anchored on published
known-answer vectors where they exist (FIPS-197, FIPS-202)."""

from mastic_tpu.aes import Aes128
from mastic_tpu.common import next_power_of_2, pack_bits, unpack_bits
from mastic_tpu.field import (Field64, Field128, poly_eval,
                              poly_eval_domain, poly_interp, poly_mul)
from mastic_tpu.keccak import sha3_256, shake128, turbo_shake128
from mastic_tpu.xof import XofFixedKeyAes128, XofTurboShake128


def test_aes128_fips197():
    cipher = Aes128(bytes.fromhex("000102030405060708090a0b0c0d0e0f"))
    ct = cipher.encrypt_block(
        bytes.fromhex("00112233445566778899aabbccddeeff"))
    assert ct.hex() == "69c4e0d86a7b0430d8cdb78070b4c55a"


def test_shake128_empty():
    assert shake128(b"", 16).hex() == "7f9c2ba4e88f827d616045507605853e"


def test_sha3_256_empty():
    assert sha3_256(b"").hex() == \
        "a7ffc6f8bf1ed76651c14756a061d662f580ff4de43b49fa82d80a4b80f8434a"


def test_turbo_shake128_streaming_matches_oneshot():
    msg = b"some message"
    stream_out = turbo_shake128(msg, 7, 100)
    from mastic_tpu.keccak import TurboShake128Stream
    s = TurboShake128Stream(msg, 7)
    got = s.read(13) + s.read(0) + s.read(87)
    assert got == stream_out


def test_turbo_shake128_rate_boundary():
    # Cross the 168-byte rate boundary in both absorb and squeeze.
    msg = bytes(range(256)) * 3
    one = turbo_shake128(msg, 1, 400)
    from mastic_tpu.keccak import TurboShake128Stream
    s = TurboShake128Stream(msg, 1)
    assert b"".join(s.read(n) for n in (167, 1, 168, 64)) == one


def test_field64_basics():
    p = Field64.MODULUS
    assert p == 2 ** 64 - 2 ** 32 + 1
    a = Field64(p - 1)
    assert (a + Field64(1)).int() == 0
    assert (Field64(0) - Field64(1)).int() == p - 1
    assert (a * a).int() == pow(p - 1, 2, p)
    assert a.inv() * a == Field64(1)
    g = Field64.gen()
    assert g ** Field64.GEN_ORDER == Field64(1)
    assert g ** (Field64.GEN_ORDER // 2) != Field64(1)


def test_field128_generator():
    g = Field128.gen()
    assert g ** Field128.GEN_ORDER == Field128(1)
    assert g ** (Field128.GEN_ORDER // 2) != Field128(1)


def test_field_codec_roundtrip():
    for field in (Field64, Field128):
        vec = field.rand_vec(7)
        assert field.decode_vec(field.encode_vec(vec)) == vec


def test_bit_vector_roundtrip():
    for field in (Field64, Field128):
        for val in (0, 1, 5, 100):
            vec = field.encode_into_bit_vector(val, 8)
            assert field.decode_from_bit_vector(vec).int() == val


def test_pack_bits():
    bits = [True, False, True, True, False, False, False, True, True]
    packed = pack_bits(bits)
    assert packed == bytes([0b10110001, 0b10000000])
    assert unpack_bits(packed, 9) == bits


def test_next_power_of_2():
    assert [next_power_of_2(n) for n in (1, 2, 3, 4, 5, 8, 9)] == \
        [1, 2, 4, 4, 8, 8, 16]


def test_poly_interp_eval_roundtrip():
    for field in (Field64, Field128):
        values = field.rand_vec(8)
        coeffs = poly_interp(field, values)
        assert poly_eval_domain(field, coeffs, 8) == values
        alpha = field.gen() ** (field.GEN_ORDER // 8)
        for k in range(8):
            assert poly_eval(field, coeffs, alpha ** k) == values[k]


def test_poly_mul():
    f = Field64
    # (1 + x) * (2 + x) = 2 + 3x + x^2
    got = poly_mul(f, [f(1), f(1)], [f(2), f(1)])
    assert got == [f(2), f(3), f(1)]


def test_xof_turboshake_next_vec_deterministic():
    xof = XofTurboShake128(bytes(32), b"dst", b"binder")
    v1 = xof.next_vec(Field64, 4)
    xof2 = XofTurboShake128(bytes(32), b"dst", b"binder")
    v2 = xof2.next_vec(Field64, 4)
    assert v1 == v2
    assert all(0 <= x.int() < Field64.MODULUS for x in v1)


def test_xof_fixed_key_aes_streaming():
    xof = XofFixedKeyAes128(bytes(16), b"dst", b"binder")
    a = xof.next(5) + xof.next(11) + xof.next(32)
    xof2 = XofFixedKeyAes128(bytes(16), b"dst", b"binder")
    b = xof2.next(48)
    assert a == b
