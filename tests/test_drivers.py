"""Driver tests: heavy hitters vs the functional oracle, attribute
metrics, and the communication report vs the measured size formulas
(SURVEY.md §2.4)."""

import numpy as np

from mastic_tpu import MasticCount, MasticSum
from mastic_tpu.drivers import (aggregate_by_attribute,
                                communication_report,
                                compute_heavy_hitters, get_threshold,
                                get_reports_from_measurements,
                                hash_attribute)
from mastic_tpu.oracle import weighted_heavy_hitters


def test_heavy_hitters_matches_oracle():
    bits = 4
    mastic = MasticCount(bits)
    ctx = b"hh driver test"
    values = [0b1001, 0b0000, 0b0000, 0b0000, 0b1001, 0b0000, 0b1100,
              0b0011, 0b1111, 0b1111]
    weights = [1, 1, 0, 1, 1, 1, 1, 1, 0, 1]
    measurements = [
        (mastic.vidpf.test_index_from_int(v, bits), w)
        for (v, w) in zip(values, weights)
    ]
    reports = get_reports_from_measurements(mastic, ctx, measurements)
    got = compute_heavy_hitters(mastic, ctx, {"default": 2}, reports)
    want = weighted_heavy_hitters(measurements, 2, bits)
    assert sorted(got) == want
    assert want  # the example is non-trivial


def test_heavy_hitters_per_prefix_thresholds():
    bits = 3
    mastic = MasticCount(bits)
    ctx = b"hh thresholds"
    values = [0b000, 0b000, 0b001, 0b100, 0b101, 0b110]
    measurements = [
        (mastic.vidpf.test_index_from_int(v, bits), 1) for v in values
    ]
    reports = get_reports_from_measurements(mastic, ctx, measurements)
    # Default threshold 2; subtree under (True,) uses threshold 1.
    thresholds = {"default": 2, (True,): 1}
    got = compute_heavy_hitters(mastic, ctx, thresholds, reports)
    assert sorted(got) == [
        (False, False, False),
        (True, False, False),
        (True, False, True),
        (True, True, False),
    ]
    assert get_threshold(thresholds, (True, False, False)) == 1
    assert get_threshold(thresholds, (False, False, True)) == 2


def test_attribute_metrics():
    mastic = MasticSum(8, 3)
    ctx = b"attr metrics"
    votes = [("United States", 1), ("Greece", 1), ("United States", 2),
             ("Greece", 0), ("United States", 0), ("India", 1),
             ("Greece", 0), ("United States", 1), ("Greece", 1),
             ("Greece", 3), ("Greece", 1)]
    reports = get_reports_from_measurements(
        mastic, ctx,
        [(hash_attribute(mastic, a), v) for (a, v) in votes])
    result = aggregate_by_attribute(
        mastic, ctx, ["Greece", "Mexico", "United States"], reports)
    assert result == [("Greece", 6), ("Mexico", 0),
                      ("United States", 4)]


def test_communication_report_matches_formulas():
    sizes = communication_report(print_fn=lambda *_: None)
    # Public-share formula: ceil(2b/8) + b*(16 + v*elem + 32)
    # (SURVEY.md §2.4, verified against the conformance vectors).
    count = sizes["MasticCount(256)"]
    assert count["public_share"] == 64 + 256 * (16 + 2 * 8 + 32)
    assert count["leader_share"] == 16 + 5 * 8
    assert count["helper_share"] == 16 + 32
    hist = sizes["MasticHistogram(32, 100, 10)"]
    assert hist["public_share"] == 8 + 32 * (16 + 101 * 16 + 32)
    assert hist["helper_share"] == 16 + 32 + 32
