#!/usr/bin/env python3
"""Minimal dependency-free lint gate (pyflakes is not in this image).

Checks, over mastic_tpu/, tests/, tools/ and the repo-root scripts:

1. every file parses (syntax);
2. unused imports (name imported but never referenced);
3. public functions/methods in the scalar protocol layer carry full
   type annotations (the local stand-in for the reference's strict
   mypy gate, /root/reference/.github/workflows/test.yml:36-44 —
   mypy.ini is shipped for environments that have mypy);
4. no `print(` in library code (drivers return data; observability is
   the metrics dict);
5. every annotation in the ANNOTATED layer resolves at runtime
   (typing.get_type_hints over each public function, class and
   method — undefined or misspelled type names fail here even
   without mypy; mypy itself remains uninstallable in this image);
6. intra-repo calls to module-level functions match the callee's
   signature — positional arity, keyword names, required args (the
   executable subset of mypy's call checking; conservative: bare
   names only, decorated defs / reassigned names / star-spreads
   skipped);
7. every MASTIC_* env lever referenced in mastic_tpu/ or bench.py is
   documented in USAGE.md, and every kernel/backend lever (read in
   mastic_tpu/ops/ or mastic_tpu/backend/) is exercised by
   tools/chip_session.sh — either by env name or by its bench.py
   flag form (--foo-bar for MASTIC_FOO_BAR).  Prevents the r5 class
   of "kernel exists but no session script exercises it";
8. the ANNOTATED list below stays in sync with mypy.ini's strict
   module set (the modules under `strict = True` with no relaxing
   override).  mypy cannot run in this image, so the two lists had
   started to drift silently; this check makes the drift a lint
   failure in both directions;
9. every metric name the telemetry registry declares
   (mastic_tpu/obs/registry.py DECLARED) appears in USAGE.md's
   "Observability" metric table — an operator reading /metrics must
   be able to look every series up, so a new metric cannot ship
   undocumented (the metric twin of check 7's lever rule);
10. USAGE.md's "Static analysis" rule table lists EXACTLY the rule
   IDs in tools.analysis._RULE_TABLE — both directions: a shipped
   rule missing from the table is undocumented, a table row whose
   rule no longer exists is stale (the analyzer twin of check 9;
   the table had only stayed in sync by luck before);
11. the refusal/shed reason-code contract: every reason literal the
   code counts into `ServiceCounters.shed_reasons` (via bump_shed /
   count_front_shed / FrontDoor.shed / shed_external) or into
   `mastic_tls_refusals_total` (the TLS_* constants in
   net/transport.py) appears in USAGE.md's reason tables, and every
   table row names a reason the code still counts — an operator
   grepping a reason off /statusz must always land on its row
   (`tls-handshake-failed` and `incomplete-body` had already drifted
   undocumented before this check existed).

Exit status 0 iff clean.  Run via `make lint` / `make ci`.
"""

import ast
import configparser
import pathlib
import re
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent

# Scalar-layer modules held to the annotation standard (the batched
# JAX layer's shapes/dtypes are documented in docstrings instead).
# Check 8 keeps this list equal to mypy.ini's strict set.
ANNOTATED = [
    "mastic_tpu/common.py", "mastic_tpu/dst.py", "mastic_tpu/field.py",
    "mastic_tpu/xof.py", "mastic_tpu/aes.py", "mastic_tpu/keccak.py",
    "mastic_tpu/vidpf.py", "mastic_tpu/mastic.py", "mastic_tpu/vdaf.py",
    "mastic_tpu/oracle.py", "mastic_tpu/flp/flp.py",
    "mastic_tpu/flp/circuits.py", "mastic_tpu/testvec_codec.py",
    "mastic_tpu/wire.py",
]

PRINT_OK = ("tools/", "bench.py", "gen_test_vec.py", "tests/",
            "__graft_entry__.py", "demo")


class ImportTracker(ast.NodeVisitor):
    def __init__(self):
        self.imported: dict = {}
        self.used: set = set()

    def visit_Import(self, node):
        for alias in node.names:
            name = (alias.asname or alias.name).split(".")[0]
            self.imported.setdefault(name, node.lineno)

    def visit_ImportFrom(self, node):
        for alias in node.names:
            if alias.name == "*":
                continue
            name = alias.asname or alias.name
            self.imported.setdefault(name, node.lineno)

    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Load):
            self.used.add(node.id)

    def visit_Attribute(self, node):
        self.generic_visit(node)


def check_file(path: pathlib.Path) -> list:
    rel = str(path.relative_to(REPO))
    problems = []
    try:
        tree = ast.parse(path.read_text(), filename=rel)
    except SyntaxError as err:
        return [f"{rel}:{err.lineno}: syntax error: {err.msg}"]

    tracker = ImportTracker()
    tracker.visit(tree)
    # Names used only inside docstring type references don't count;
    # __all__ re-exports do.
    exported = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for target in node.targets:
                if isinstance(target, ast.Name) and target.id == "__all__":
                    if isinstance(node.value, (ast.List, ast.Tuple)):
                        for elt in node.value.elts:
                            if isinstance(elt, ast.Constant):
                                exported.add(elt.value)
    if not rel.endswith("__init__.py"):
        for (name, lineno) in sorted(tracker.imported.items(),
                                     key=lambda kv: kv[1]):
            if name not in tracker.used and name not in exported:
                problems.append(f"{rel}:{lineno}: unused import "
                                f"'{name}'")

    if rel in ANNOTATED:
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if node.name.startswith("_"):
                continue
            args = node.args
            all_args = args.posonlyargs + args.args + args.kwonlyargs
            missing = [a.arg for a in all_args
                       if a.annotation is None
                       and a.arg not in ("self", "cls")]
            if missing:
                problems.append(
                    f"{rel}:{node.lineno}: public function "
                    f"'{node.name}' missing annotations: {missing}")
            if node.returns is None and node.name != "__init__":
                problems.append(
                    f"{rel}:{node.lineno}: public function "
                    f"'{node.name}' missing return annotation")

    if not any(rel.startswith(ok) or ok in rel for ok in PRINT_OK):
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "print"
                    and not _prints_to_stderr(node)):
                problems.append(f"{rel}:{node.lineno}: print() to "
                                "stdout in library code")
    return problems


def _prints_to_stderr(node: ast.Call) -> bool:
    """Diagnostics on stderr are fine; stdout pollution is the smell."""
    for kw in node.keywords:
        if kw.arg == "file" and isinstance(kw.value, ast.Attribute) \
                and kw.value.attr == "stderr":
            return True
    return False


def check_annotations_resolve() -> list:
    """Check 5: every annotation in the ANNOTATED layer resolves at
    runtime.  get_type_hints evaluates the annotation expressions
    against the module globals, so a typo'd or un-imported type name
    raises here — the executable subset of mypy's name resolution."""
    import importlib
    import inspect
    import typing

    problems = []
    sys.path.insert(0, str(REPO))
    for rel in ANNOTATED:
        mod_name = rel[:-3].replace("/", ".")
        try:
            mod = importlib.import_module(mod_name)
        except Exception as exc:
            problems.append(f"{rel}: module does not import: "
                            f"{type(exc).__name__}: {exc}")
            continue
        def unwrap(member):
            """classmethod/staticmethod descriptors and properties
            hide their function from inspect.isfunction — unwrap, or
            their annotations would silently escape the check."""
            if isinstance(member, (classmethod, staticmethod)):
                return member.__func__
            if isinstance(member, property):
                return member.fget
            return member

        targets = []
        for (name, obj) in vars(mod).items():
            if getattr(obj, "__module__", None) != mod_name:
                continue
            if inspect.isfunction(obj):
                targets.append((name, obj))
            elif inspect.isclass(obj):
                targets.append((name, obj))
                for (mname, member) in vars(obj).items():
                    member = unwrap(member)
                    if inspect.isfunction(member):
                        targets.append((f"{name}.{mname}", member))
        for (tname, target) in targets:
            try:
                typing.get_type_hints(target)
            except Exception as exc:
                problems.append(
                    f"{rel}: annotation on '{tname}' does not "
                    f"resolve: {type(exc).__name__}: {exc}")
    return problems


def _module_name(path: pathlib.Path) -> str:
    rel = path.relative_to(REPO)
    return str(rel)[:-3].replace("/", ".")


def _collect_defs(tree: ast.Module) -> dict:
    """Module-level plain functions only (no methods — `self` and
    inheritance are out of scope; no decorated defs — decorators may
    change the signature)."""
    defs = {}
    for node in tree.body:
        if isinstance(node, ast.FunctionDef) and not node.decorator_list:
            defs[node.name] = node.args
    return defs


def _signature_problem(name: str, a: ast.arguments,
                       call: ast.Call) -> str:
    """Arity/keyword mismatch text, or '' if the call fits.  Calls
    spreading *args/**kwargs are the caller's business — skipped."""
    if any(isinstance(x, ast.Starred) for x in call.args) \
            or any(k.arg is None for k in call.keywords):
        return ""
    pos_params = [p.arg for p in a.posonlyargs + a.args]
    kw_names = set(pos_params[len(a.posonlyargs):]) \
        | {p.arg for p in a.kwonlyargs}
    if a.vararg is None and len(call.args) > len(pos_params):
        return (f"takes {len(pos_params)} positional arg(s), "
                f"call passes {len(call.args)}")
    for k in call.keywords:
        if k.arg not in kw_names and a.kwarg is None:
            return f"got unexpected keyword '{k.arg}'"
    supplied = set(pos_params[:len(call.args)]) \
        | {k.arg for k in call.keywords}
    n_defaults = len(a.defaults)
    required = pos_params[:len(pos_params) - n_defaults]
    missing = [p for p in required if p not in supplied]
    if missing:
        return f"missing required arg(s) {missing}"
    return ""


def check_call_signatures(files: list) -> list:
    """Check 6: intra-repo calls to module-level functions match the
    callee's signature (positional arity, keyword names, required
    args) — the executable subset of mypy's call checking.  Only
    calls through a bare name that is a same-module def or a
    `from <repo module> import name`; names locally reassigned and
    star-spread calls are skipped."""
    trees = {}
    for path in files:
        try:
            trees[path] = ast.parse(path.read_text())
        except SyntaxError:
            continue  # check 1 reports it
    defs_by_module = {_module_name(p): _collect_defs(t)
                      for (p, t) in trees.items()}

    problems = []
    for (path, tree) in trees.items():
        mod = _module_name(path)
        pkg_parts = mod.split(".")[:-1]
        # name -> (defining module, name there)
        env = {n: (mod, n) for n in defs_by_module.get(mod, {})}
        reassigned = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                if node.level:
                    base = pkg_parts[:len(pkg_parts) - node.level + 1]
                    target = ".".join(base + ([node.module]
                                              if node.module else []))
                else:
                    target = node.module or ""
                if target in defs_by_module:
                    for alias in node.names:
                        if alias.name in defs_by_module[target]:
                            env[alias.asname or alias.name] = \
                                (target, alias.name)
            elif isinstance(node, (ast.Assign, ast.AugAssign,
                                   ast.AnnAssign, ast.For)):
                targets = (node.targets
                           if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            reassigned.add(n.id)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                for arg in (node.args.posonlyargs + node.args.args
                            + node.args.kwonlyargs):
                    reassigned.add(arg.arg)
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)):
                continue
            name = node.func.id
            if name in reassigned or name not in env:
                continue
            (dmod, dname) = env[name]
            msg = _signature_problem(
                name, defs_by_module[dmod][dname], node)
            if msg:
                problems.append(
                    f"{path.relative_to(REPO)}:{node.lineno}: call to "
                    f"{dmod}.{dname} {msg}")
    return problems


_LEVER_RE = re.compile(r"MASTIC_[A-Z][A-Z0-9_]*")


def check_env_levers() -> list:
    """Check 7: lever coverage.  A MASTIC_* env var referenced
    anywhere in mastic_tpu/ or bench.py must be documented in
    USAGE.md; one referenced in the kernel/backend layer (ops/ or
    backend/ — the compute-path levers a chip session must measure)
    must additionally appear in tools/chip_session.sh, either
    verbatim or as the bench.py flag it maps to."""
    lever_files = sorted((REPO / "mastic_tpu").rglob("*.py"))
    lever_files.append(REPO / "bench.py")
    levers: dict = {}          # name -> (first file, is_kernel_lever)
    for path in lever_files:
        rel = str(path.relative_to(REPO))
        kernel = rel.startswith(("mastic_tpu/ops/",
                                 "mastic_tpu/backend/"))
        for name in _LEVER_RE.findall(path.read_text()):
            (seen_rel, seen_kernel) = levers.get(name, (rel, False))
            levers[name] = (seen_rel, seen_kernel or kernel)

    usage = (REPO / "USAGE.md").read_text()
    session = (REPO / "tools" / "chip_session.sh").read_text()
    problems = []
    for (name, (rel, kernel)) in sorted(levers.items()):
        if name not in usage:
            problems.append(
                f"{rel}: env lever {name} is not documented in "
                f"USAGE.md")
        flag = "--" + name[len("MASTIC_"):].lower().replace("_", "-")
        if kernel and name not in session and flag not in session:
            problems.append(
                f"{rel}: kernel lever {name} is not exercised by "
                f"tools/chip_session.sh (neither {name} nor its "
                f"bench flag {flag} appears in the matrix)")
    return problems


def _strict_mypy_modules(ini_path: pathlib.Path = None) -> set:
    """Module names mypy.ini holds to the full strict standard: under
    the global `strict = True` with no per-module override relaxing
    them (ignore_errors or disallow_untyped_defs).  __init__ re-export
    shims are skipped — they hold no function signatures."""
    cfg = configparser.ConfigParser()
    cfg.read(ini_path or REPO / "mypy.ini")
    relaxed_patterns = []
    for section in cfg.sections():
        if not section.startswith("mypy-"):
            continue
        sub = cfg[section]
        if sub.getboolean("ignore_errors", fallback=False) \
                or not sub.getboolean("disallow_untyped_defs",
                                      fallback=True):
            relaxed_patterns.append(section[len("mypy-"):])

    def relaxed(module: str) -> bool:
        for pat in relaxed_patterns:
            if pat.endswith(".*"):
                if module == pat[:-2] or module.startswith(pat[:-1]):
                    return True
            elif module == pat:
                return True
        return False

    strict = set()
    for path in sorted((REPO / "mastic_tpu").rglob("*.py")):
        if path.name == "__init__.py":
            continue
        module = str(path.relative_to(REPO))[:-3].replace("/", ".")
        if not relaxed(module):
            strict.add(module)
    return strict


def check_metric_docs() -> list:
    """Check 9: every declared registry series is documented.  The
    registry module is import-cheap (stdlib only), so importing it to
    read DECLARED is the same pattern check 5 uses."""
    sys.path.insert(0, str(REPO))
    from mastic_tpu.obs.registry import declared_metric_names

    usage = (REPO / "USAGE.md").read_text()
    problems = []
    for name in declared_metric_names():
        if name not in usage:
            problems.append(
                f"mastic_tpu/obs/registry.py: metric {name} is "
                f"declared but not documented in USAGE.md's "
                f"Observability metric table")
    return problems


_RULE_ROW_RE = re.compile(r"^\|\s*`([A-Z]{2}\d{3})`")


def check_rule_table_docs() -> list:
    """Check 10: the USAGE.md analyzer rule table == the analyzer's
    _RULE_TABLE.  The table rows are the lines starting `| \\`XX000\\``
    inside the "Static analysis" section (same import-the-source-of-
    truth pattern as check 9 — tools.analysis is stdlib-only)."""
    sys.path.insert(0, str(REPO))
    from tools.analysis import _RULE_TABLE

    usage = (REPO / "USAGE.md").read_text()
    in_section = False
    documented = set()
    for line in usage.splitlines():
        if line.startswith("## "):
            in_section = line.startswith("## Static analysis")
            continue
        if in_section:
            m = _RULE_ROW_RE.match(line)
            if m:
                documented.add(m.group(1))
    problems = []
    for rule in sorted(set(_RULE_TABLE) - documented):
        problems.append(
            f"tools/analysis: rule {rule} is shipped but missing "
            f"from USAGE.md's Static-analysis rule table")
    for rule in sorted(documented - set(_RULE_TABLE)):
        problems.append(
            f"USAGE.md: rule-table row {rule} names a rule the "
            f"analyzer no longer ships — remove the stale row")
    return problems


# Sinks whose string-literal (or ALL_CAPS-constant) arguments are
# shed reasons; the TLS refusal vocabulary is the TLS_* constant set
# in net/transport.py (the reasons reach _count_refusal through
# exception attributes, so the constants ARE the source of truth).
_SHED_SINKS = {"bump_shed", "count_front_shed", "shed",
               "shed_external"}
_REASON_ROW_RE = re.compile(r"^\|\s*`([a-z0-9]+(?:-[a-z0-9]+)+)`")
_REASON_SECTIONS = ("## Collector service", "## Network front",
                    "## Durability",
                    "## Transport security")


def _counted_reasons() -> dict:
    """reason literal -> file that counts it, from the code."""
    files = sorted((REPO / "mastic_tpu").rglob("*.py"))
    trees = {}
    consts: dict = {}      # ALL_CAPS name -> hyphenated str value
    for path in files:
        rel = str(path.relative_to(REPO))
        try:
            trees[rel] = ast.parse(path.read_text())
        except SyntaxError:
            continue  # check 1 reports it
        for node in trees[rel].body:
            if isinstance(node, ast.Assign) \
                    and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id.isupper() \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str) \
                    and "-" in node.value.value:
                consts[node.targets[0].id] = node.value.value

    reasons: dict = {}
    for (rel, tree) in trees.items():
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _SHED_SINKS):
                continue
            for arg in node.args:
                if isinstance(arg, ast.Constant) \
                        and isinstance(arg.value, str) \
                        and "-" in arg.value:
                    reasons.setdefault(arg.value, rel)
                elif isinstance(arg, ast.Name) \
                        and arg.id in consts:
                    reasons.setdefault(consts[arg.id], rel)
    tls_rel = "mastic_tpu/net/transport.py"
    for (name, value) in consts.items():
        if name.startswith("TLS_") and value.startswith("tls-"):
            reasons.setdefault(value, tls_rel)
    return reasons


def check_reason_docs() -> list:
    """Check 11: the reason-code contract.  The kebab-case rows of
    the reason tables in USAGE.md's service/network/transport
    sections must equal the reason literals the code counts — both
    directions (same shape as check 10)."""
    counted = _counted_reasons()
    usage = (REPO / "USAGE.md").read_text()
    in_section = False
    documented = set()
    for line in usage.splitlines():
        if line.startswith("## "):
            in_section = line.startswith(_REASON_SECTIONS)
            continue
        if in_section:
            m = _REASON_ROW_RE.match(line)
            if m:
                documented.add(m.group(1))
    problems = []
    for reason in sorted(set(counted) - documented):
        problems.append(
            f"{counted[reason]}: shed/refusal reason "
            f"'{reason}' is counted but has no row in USAGE.md's "
            f"reason tables")
    for reason in sorted(documented - set(counted)):
        problems.append(
            f"USAGE.md: reason-table row '{reason}' names a reason "
            f"the code no longer counts — remove the stale row")
    return problems


def check_mypy_sync() -> list:
    """Check 8: ANNOTATED == mypy.ini's strict module set, so the
    runtime annotation gate (checks 3/5) covers exactly the modules
    real CI would hold to strict mypy."""
    annotated = {rel[:-3].replace("/", ".") for rel in ANNOTATED}
    strict = _strict_mypy_modules()
    problems = []
    for module in sorted(strict - annotated):
        problems.append(
            f"mypy.ini: {module} is mypy-strict but missing from "
            f"tools/lint.py ANNOTATED (add it, or relax it in "
            f"mypy.ini with a reason)")
    for module in sorted(annotated - strict):
        problems.append(
            f"tools/lint.py: {module} is in ANNOTATED but relaxed in "
            f"mypy.ini (drop the override, or remove it from "
            f"ANNOTATED)")
    return problems


def main() -> int:
    roots = [REPO / "mastic_tpu", REPO / "tests", REPO / "tools"]
    files = [REPO / "bench.py", REPO / "__graft_entry__.py"]
    fixtures = REPO / "tests" / "fixtures"
    for root in roots:
        files += sorted(p for p in root.rglob("*.py")
                        if fixtures not in p.parents)
    problems = []
    for path in files:
        problems += check_file(path)
    problems += check_annotations_resolve()
    problems += check_call_signatures(files)
    problems += check_env_levers()
    problems += check_mypy_sync()
    problems += check_metric_docs()
    problems += check_rule_table_docs()
    problems += check_reason_docs()
    for problem in problems:
        print(problem)
    print(f"lint: {len(files)} files, {len(problems)} problem(s)")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
