"""Weighted heavy hitters: the multi-round collector loop.

Functionally equivalent to the reference driver
(/root/reference/poc/examples.py:13-91) — per level, aggregate over the
candidate-prefix frontier, threshold-prune, expand survivors — but the
per-report prep loop is replaced by one batched device round per level
(both aggregators' prep + accept + aggregation on device; the FLP
verifier exchange on the weight-check round crosses the host boundary,
as it does between real aggregators).

Thresholds: a dict mapping prefix tuples to ints with a "default" key;
the threshold for a prefix is that of its *longest strict ancestor*
present in the dict, else the default (reference examples.py:26-34,
spec draft-mouris-cfrg-mastic.md:1535-1572).
"""

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..common import gen_rand, vec_add
from ..mastic import Mastic
from ..backend.mastic_jax import BatchedMastic, ReportBatch


def get_reports_from_measurements(mastic: Mastic, ctx: bytes,
                                  measurements: Sequence) -> list:
    """Client side: shard each measurement with fresh randomness."""
    reports = []
    for measurement in measurements:
        nonce = gen_rand(mastic.NONCE_SIZE)
        rand = gen_rand(mastic.RAND_SIZE)
        (public_share, input_shares) = mastic.shard(
            ctx, measurement, nonce, rand)
        reports.append((nonce, public_share, input_shares))
    return reports


def get_threshold(thresholds: dict, prefix: tuple) -> int:
    """Longest-strict-ancestor threshold lookup."""
    for level in reversed(range(len(prefix) - 1)):
        if prefix[:level + 1] in thresholds:
            return thresholds[prefix[:level + 1]]
    return thresholds["default"]


def run_round(bm: BatchedMastic, verify_key: bytes, ctx: bytes,
              agg_param, batch: ReportBatch,
              accept_out: Optional[list] = None) -> list:
    """One aggregation round on the batched backend: both preps,
    checks, masked aggregation, unshard.  Returns the per-prefix
    aggregate result; appends the accept mask to `accept_out`."""
    (_level, _prefixes, do_weight_check) = agg_param
    (p0, p1) = jax.jit(
        lambda b: bm.prep_both(verify_key, ctx, agg_param, b))(batch)
    _require_ok(p0, p1)
    if do_weight_check:
        verifiers = (bm.flp_query_host(p0), bm.flp_query_host(p1))
    else:
        verifiers = (None, None)
    accept = bm.accept_mask(p0, p1, do_weight_check, *verifiers)
    if accept_out is not None:
        accept_out.append(accept)
    agg_shares = [
        bm.agg_share_to_host(
            bm.aggregate(p.out_share, jnp.asarray(accept)))
        for p in (p0, p1)
    ]
    num = int(np.asarray(accept).sum())
    return bm.m.unshard(agg_param, agg_shares, num)


def _require_ok(p0, p1) -> None:
    """Rejection sampling fired (~2^-32/element): the scalar fallback
    for affected reports is not wired up yet, so fail loudly rather
    than silently diverge."""
    if not (bool(np.all(np.asarray(p0.ok)))
            and bool(np.all(np.asarray(p1.ok)))):
        raise NotImplementedError(
            "XOF rejection-sampling fallback not yet implemented for "
            "this batch")


def compute_heavy_hitters(mastic: Mastic, ctx: bytes, thresholds: dict,
                          reports: list,
                          verify_key: Optional[bytes] = None) -> list:
    """The full collector loop (reference examples.py:37-91)."""
    if verify_key is None:
        verify_key = gen_rand(mastic.VERIFY_KEY_SIZE)
    bm = BatchedMastic(mastic)
    batch = bm.marshal_reports(reports)

    prefixes: list = [(False,), (True,)]
    prev_agg_params: list = []
    heavy_hitters: list = []
    for level in range(mastic.vidpf.BITS):
        if not prefixes:
            break
        agg_param = (level, tuple(prefixes), level == 0)
        assert mastic.is_valid(agg_param, prev_agg_params)
        agg_result = run_round(bm, verify_key, ctx, agg_param, batch)
        prev_agg_params.append(agg_param)

        survivors = [
            prefix for (prefix, count) in zip(prefixes, agg_result)
            if count >= get_threshold(thresholds, prefix)
        ]
        if level < mastic.vidpf.BITS - 1:
            prefixes = [p + (bit,) for p in survivors
                        for bit in (False, True)]
        else:
            heavy_hitters = survivors
    return heavy_hitters
