"""The USAGE.md walkthrough snippets, executed.

USAGE.md promises a user of the reference that each of its workflows
(reference poc/examples.py:37-280) runs here as written; these tests
keep those snippets from rotting.  Shapes are the doc's own.
"""

import pytest

pytestmark = pytest.mark.slow

from mastic_tpu import MasticCount, MasticSum
from mastic_tpu.drivers import (aggregate_by_attribute,
                                compute_heavy_hitters,
                                get_reports_from_measurements,
                                hash_attribute)
from mastic_tpu.oracle import weighted_heavy_hitters


def test_usage_plain_heavy_hitters():
    m = MasticCount(16)
    meas = [(m.vidpf.test_index_from_int(v, 16), 1)
            for v in (7, 7, 7, 21, 21, 99)]
    reports = get_reports_from_measurements(m, b"app", meas)
    hitters = compute_heavy_hitters(m, b"app", {"default": 2}, reports)
    expected = {m.vidpf.test_index_from_int(7, 16),
                m.vidpf.test_index_from_int(21, 16)}
    assert set(hitters) == expected
    # The functional oracle agrees (USAGE's ground-truth section).
    assert set(weighted_heavy_hitters(meas, 2, 16)) == expected


def test_usage_attribute_metrics():
    m = MasticSum(32, 100)
    meas = [(hash_attribute(m, "checkout.html"), 4),
            (hash_attribute(m, "landing.html"), 9),
            (hash_attribute(m, "checkout.html"), 1)]
    reports = get_reports_from_measurements(m, b"metrics", meas)
    totals = aggregate_by_attribute(
        m, b"metrics", ["checkout.html", "landing.html"], reports)
    assert dict(totals) == {"checkout.html": 5, "landing.html": 9}
