"""CC001 bad fixture: unlocked mutation of state shared across
thread roots (push runs on the main thread, _loop on the worker)."""
import threading


class Worker:
    def __init__(self):
        self.lock = threading.Lock()
        self.items = []
        self.thread = threading.Thread(target=self._loop)

    def _loop(self):
        with self.lock:
            self.items.pop()

    def push(self, x):
        self.items.append(x)
