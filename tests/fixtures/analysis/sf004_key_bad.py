"""Known-bad (ISSUE 14, credential flavor): a TLS PRIVATE KEY's
bytes leaving the process over a socket (SF004) — key material may
only ever reach disk through the cert tooling's openssl calls (file
paths, 0600), never a wire."""


def ship_credential(sock, private_key):
    sock.sendall(private_key)
