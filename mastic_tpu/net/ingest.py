"""The DAP-shaped upload endpoint (ISSUE 11 tentpole, leg a): a
threaded HTTP front that turns `CollectorService.submit()` into a
network service.

Framing follows the DAP upload flow shape (draft-ietf-ppm-dap: the
client PUTs one media-typed report to a per-task resource and gets a
status code, never a body it must parse to learn success):

    PUT /v1/tenants/{tenant}/reports
        Content-Type: application/mastic-report-bundle
        <wire.frame(leader view) || wire.frame(helper view)>

    201 admitted        {"status": "admitted"}
    202 queued          {"status": "queued"}     (ingest front armed:
                        the verdict lands asynchronously in counters)
    400 quarantined     {"error": "quarantined", "reason": <r8 code>}
    404 unknown tenant  {"error": "unknown-tenant"}
    411 no length       {"error": "length-required"}
    413 oversized       {"error": "body-too-large", "limit_bytes": N}
    415 wrong media     {"error": "unsupported-media-type", ...}
    429 shed            {"error": "shed", "reason": <shed reason>}
                        + Retry-After     (quota, queue-full, rate)
    503 overloaded      {"error": "shed", "reason":
                        "connections-exhausted"} + Retry-After
    503 brownout        {"error": "shed", "reason": "wal-full" |
                        "wal-degraded"} + Retry-After  (ISSUE 18: the
                        admission WAL cannot make the upload durable —
                        ENOSPC / fsync failure; reads and status keep
                        serving, acked reports stay safe)

Every error body is structured JSON built from FIXED strings, the r8
reason-code names and integer limits — nothing derived from tenant
key material or report contents crosses back out (the SF004
secret-flow pass covers this module; the error path is proven
secret-free, not assumed).  Every refusal lands in the tenant's
`ServiceCounters.shed_reasons` / quarantine ledger via the service
seam, and every request increments
`mastic_net_http_requests_total{code}` and observes
`mastic_net_admission_latency_ms` — the door is never silent.

Fault injection (`MASTIC_FAULTS`, party ``collector``) reaches this
edge: checkpoint ``http_accept`` fires per request (kill/hang/delay),
and ``http_body`` is an `on_blob` content seam over the received body
(truncate/corrupt model a mangled upload in flight — which must
quarantine with an attributed reason, never admit).

The server is a stdlib `ThreadingHTTPServer` (the statusz idiom): a
daemon thread per connection, every socket read deadline-bounded
(`NetConfig.io_timeout`), concurrency bounded by the admission
controller's connection ceiling.  TLS termination is the fronting
proxy's job in a real deployment — exactly where DAP puts it.
"""

import json
import math
import re
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional


class _IdleTimeout(Exception):
    """Whole-body idle budget exhausted mid-read (shed reason
    `idle-timeout`)."""

from ..drivers import faults as faults_mod
from ..drivers.service import ADMITTED, QUARANTINED, QUEUED, SHED
from ..drivers.wal import (REASON_WAL_DEGRADED, REASON_WAL_FULL,
                           WalUnavailable)
from ..obs import trace as obs_trace
from ..obs.registry import get_registry
from .admission import (AdmissionController, NetConfig,
                        REASON_BODY_TOO_LARGE, REASON_CONNS_EXHAUSTED,
                        REASON_IDLE_TIMEOUT, REASON_INCOMPLETE_BODY,
                        REASON_RATE_LIMITED)

MEDIA_TYPE = "application/mastic-report-bundle"
API_VERSION = 1

_REPORTS_RE = re.compile(r"^/v1/tenants/([A-Za-z0-9_.-]{1,64})"
                         r"/reports$")
_EPOCH_RE = re.compile(r"^/v1/tenants/([A-Za-z0-9_.-]{1,64})"
                       r"/epoch$")
_DRAIN_PATH = "/v1/admin/drain"

# submit() verdict -> (HTTP code, body builder).
_STATUS_CODES = {ADMITTED: 201, QUEUED: 202, QUARANTINED: 400,
                 SHED: 429}


class _UploadHandler(BaseHTTPRequestHandler):
    server_version = "mastic-upload/1"
    protocol_version = "HTTP/1.1"
    # Small request/response pairs on keep-alive connections hit the
    # Nagle x delayed-ACK interaction hard (a measured, uniform
    # ~40 ms floor on loopback); admission latency is the SLO metric,
    # so the artifact would dominate every quantile.
    disable_nagle_algorithm = True

    # -- plumbing --------------------------------------------------

    def setup(self) -> None:
        super().setup()
        # Every read/write on this connection is deadline-bounded: a
        # client that stalls mid-body costs one handler thread for
        # io_timeout, never forever.
        front: "UploadFront" = self.server.front  # type: ignore
        self.connection.settimeout(front.cfg.io_timeout)
        self._body_consumed = True

    def _respond(self, code: int, body: dict,
                 retry_after: Optional[float] = None) -> None:
        data = json.dumps(body, sort_keys=True).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        if retry_after is not None:
            self.send_header("Retry-After",
                             str(max(1, math.ceil(retry_after))))
        if not self._body_consumed:
            # Keep-alive would misparse the unread request body as
            # the next request line; refuse-and-close is the honest
            # framing.
            self.send_header("Connection", "close")
            self.close_connection = True
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, fmt: str, *args) -> None:
        """Per-request stderr chatter off; the registry series and
        the net.request span are the record."""

    # -- routes ----------------------------------------------------

    def do_GET(self) -> None:  # noqa: N802 (http.server API)
        if self.path.split("?", 1)[0] == "/healthz":
            self._respond(200, {"status": "ok"})
        else:
            self._respond(404, {"error": "unknown-route"})

    def do_POST(self) -> None:  # noqa: N802 (http.server API)
        """Operator-plane controls (epoch cut, drain) — armed only
        when the embedding process opts in (`admin=True`); a public
        front 404s them indistinguishably from unknown routes.

        Controls are REQUESTS, not executions: the handler thread
        only enqueues; the embedding process's scheduler thread pops
        and acts (`UploadFront.pop_epoch_requests`).  Scheduler-plane
        state is therefore never touched from a server thread — the
        same plane separation the statusz surface keeps, and what
        the CC001 whole-program pass holds this module to."""
        front: "UploadFront" = self.server.front  # type: ignore
        path = self.path.split("?", 1)[0]
        if not front.admin:
            self._respond(404, {"error": "unknown-route"})
            return
        m = _EPOCH_RE.match(path)
        if m is not None:
            tenant = m.group(1)
            if tenant not in front.service.tenants:
                self._respond(404, {"error": "unknown-tenant"})
                return
            if front.request_epoch(tenant):
                self._respond(202, {"status": "epoch-requested"})
            else:
                self._respond(429, {"error": "shed",
                                    "reason": "control-queue-full"},
                              retry_after=1.0)
            return
        if path == _DRAIN_PATH:
            front.drain_requested.set()
            self._respond(202, {"status": "draining"})
            return
        self._respond(404, {"error": "unknown-route"})

    def do_PUT(self) -> None:  # noqa: N802 (http.server API)
        front: "UploadFront" = self.server.front  # type: ignore
        if not front.controller.try_acquire_connection():
            self._body_consumed = False
            front.count_request(503)
            front.shed(self._path_tenant(), REASON_CONNS_EXHAUSTED)
            self._respond(503, {"error": "shed",
                                "reason": REASON_CONNS_EXHAUSTED},
                          retry_after=1.0)
            return
        front.publish_connections()
        try:
            self._serve_put(front)
        except Exception:
            # A handler must survive anything one hostile request can
            # throw; the response carries NO detail (error internals
            # could echo request bytes) — the trace event is the
            # diagnostic record.
            obs_trace.event("net_internal_error")
            try:
                self._body_consumed = False
                front.count_request(500)
                self._respond(500, {"error": "internal"})
            except OSError:
                # Client already gone; nothing to tell it — but the
                # drop is recorded, not silent.
                obs_trace.event("net_client_gone")
        finally:
            front.controller.release_connection()
            front.publish_connections()

    def _read_body(self, front: "UploadFront", length: int) -> bytes:
        """The request body under ONE whole-body idle budget
        (`NetConfig.idle_timeout` / `MASTIC_NET_IDLE_TIMEOUT`): each
        chunk read is still bounded by io_timeout, but the budget is
        shared, so trickling a byte every few seconds cannot hold the
        connection slot past the budget.  Raises `_IdleTimeout` when
        the budget is gone with bytes still owed."""
        from ..drivers.session import Deadline

        cfg = front.cfg
        deadline = Deadline(cfg.idle_timeout)
        buf = bytearray()
        while len(buf) < length:
            rem = deadline.remaining()
            if rem <= 0.0:
                raise _IdleTimeout()
            self.connection.settimeout(min(rem, cfg.io_timeout))
            try:
                chunk = self.rfile.read(min(length - len(buf),
                                            1 << 16))
            except (TimeoutError, socket.timeout):
                raise _IdleTimeout()
            if not chunk:
                break   # EOF short of the promise: incomplete-body
            buf += chunk
        return bytes(buf)

    def _path_tenant(self) -> Optional[str]:
        m = _REPORTS_RE.match(self.path.split("?", 1)[0])
        return m.group(1) if m is not None else None

    def _client_ip(self, front: "UploadFront") -> str:
        if front.cfg.trust_forwarded:
            fwd = self.headers.get("X-Forwarded-For")
            if fwd:
                return fwd.split(",")[0].strip()
        return self.client_address[0]

    def _serve_put(self, front: "UploadFront") -> None:
        front._checkpoint("http_accept")
        t0 = time.perf_counter()
        self._body_consumed = False
        code = 500
        try:
            (code, body, retry_after) = self._admit(front)
            self._respond(code, body, retry_after=retry_after)
        finally:
            latency_ms = (time.perf_counter() - t0) * 1e3
            front.count_request(code, latency_ms)
            # The request's r12 span, via the single-call finished
            # form (record_span): handler threads never mutate a
            # live span, so the tracer's ownership discipline holds
            # at this edge too.
            obs_trace.get_tracer().record_span(
                "net.request", duration_ms=latency_ms,
                method="PUT", code=code)

    def _admit(self, front: "UploadFront") -> tuple:
        """The whole door, in gate order; returns (code, body,
        retry_after)."""
        cfg = front.cfg
        tenant = self._path_tenant()
        if tenant is None:
            return (404, {"error": "unknown-route"}, None)
        if tenant not in front.service.tenants:
            return (404, {"error": "unknown-tenant"}, None)

        ctype = (self.headers.get("Content-Type") or "").strip()
        base = ctype.split(";", 1)[0].strip().lower()
        if base != MEDIA_TYPE:
            return (415, {"error": "unsupported-media-type",
                          "expect": MEDIA_TYPE}, None)

        raw_len = self.headers.get("Content-Length")
        try:
            length = int(raw_len)
        except (TypeError, ValueError):
            return (411, {"error": "length-required"}, None)
        if length < 0:
            return (411, {"error": "length-required"}, None)
        if length > cfg.max_body:
            front.shed(tenant, REASON_BODY_TOO_LARGE)
            return (413, {"error": "body-too-large",
                          "limit_bytes": cfg.max_body}, None)

        (ok, retry_after) = front.controller.admit(
            self._client_ip(front))
        if not ok:
            front.shed(tenant, REASON_RATE_LIMITED)
            return (429, {"error": "shed",
                          "reason": REASON_RATE_LIMITED}, retry_after)

        try:
            body = self._read_body(front, length)
        except _IdleTimeout:
            # ISSUE 14 satellite: a client trickling bytes under the
            # per-read io_timeout used to hold a connection-ceiling
            # slot indefinitely; the whole-body idle budget sheds it
            # reason-coded instead (tests prove with a slow-loris).
            front.shed(tenant, REASON_IDLE_TIMEOUT)
            return (408, {"error": "shed",
                          "reason": REASON_IDLE_TIMEOUT}, None)
        except OSError:
            body = b""
        if len(body) != length:
            # The client promised more bytes than it delivered; the
            # connection closes (keep-alive framing is gone either
            # way) and the drop is attributed, not silent.
            front.shed(tenant, REASON_INCOMPLETE_BODY)
            return (400, {"error": REASON_INCOMPLETE_BODY}, None)
        self._body_consumed = True
        if front.injector is not None:
            # The in-flight mutation seam: a truncated/corrupted body
            # reaches submit() below and must quarantine with an
            # attributed reason — never admit.
            body = front.injector.on_blob("http_body", body)

        if front._persist is not None:
            # Durability gate (ISSUE 18): the upload body goes into
            # the admission WAL and this thread blocks until its
            # record's fsync — BEFORE submit, so a failed append is
            # a clean reason-coded 503 with no half-admitted state,
            # and a crash after this point leaves a record recovery
            # replays (the client's retry then acks idempotently).
            try:
                front._persist(tenant, body)
            except WalUnavailable as exc:
                if exc.reason == REASON_WAL_FULL:
                    front.shed(tenant, REASON_WAL_FULL)
                    return (503, {"error": "shed",
                                  "reason": REASON_WAL_FULL},
                            exc.retry_after)
                front.shed(tenant, REASON_WAL_DEGRADED)
                return (503, {"error": "shed",
                              "reason": REASON_WAL_DEGRADED},
                        exc.retry_after)

        (status, detail) = front.service.submit(tenant, body)
        code = _STATUS_CODES[status]
        if status in (ADMITTED, QUEUED):
            # Durability hook (serve.py --snapshot): the embedding
            # process persists BEFORE the ack leaves, so a client
            # that got a 2xx never loses that upload to a crash.
            front.notify_admitted(tenant)
            return (code, {"status": status}, None)
        if status == QUARANTINED:
            return (code, {"error": "quarantined", "reason": detail},
                    None)
        return (code, {"error": "shed", "reason": detail}, 1.0)


class UploadFront:
    """The embedding process's handle (the StatusServer idiom):
    construct over a live `CollectorService`, `start()` binds and
    serves on a daemon thread, `stop()` shuts the listener down.
    Port 0 binds an ephemeral port (`self.port` has the real one)."""

    def __init__(self, service, config: Optional[NetConfig] = None,
                 port: int = 0, host: str = "127.0.0.1",
                 injector=None, admin: bool = False,
                 on_admitted=None, registry=None, persist=None):
        self.service = service
        # `cfg`, not `config`: see AdmissionController — attr-name
        # aliasing with jax.config would muddy the CC001 model.
        self.cfg = config or NetConfig.from_env()
        self.controller = AdmissionController(self.cfg)
        self.injector = (injector if injector is not None
                         else faults_mod.injector_from_env("collector"))
        self.admin = admin
        self.registry = (registry if registry is not None
                         else get_registry())
        self.drain_requested = threading.Event()
        self.requested_port = port
        self.host = host
        self.port: Optional[int] = None
        self._on_admitted = on_admitted
        # Durability gate (ISSUE 18): `(tenant, body) -> None`,
        # called before submit(); blocks until the upload is
        # fsync-durable, raises WalUnavailable for the reason-coded
        # brownout 503.  serve.py passes AdmissionWal.append_report.
        self._persist = persist
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        # Epoch-cut requests the admin endpoint queued (BOUNDED: a
        # hammered control endpoint sheds, it does not grow), popped
        # and executed by the embedding scheduler thread.
        self._control_mu = threading.Lock()
        self._epoch_requests: list = []
        self._control_bound = 64

    # -- lifecycle -------------------------------------------------

    def start(self) -> "UploadFront":
        self._httpd = ThreadingHTTPServer(
            (self.host, self.requested_port), _UploadHandler)
        # Publication handoff (the StatusServer pattern): `front` is
        # written once, strictly before Thread.start() below, and
        # never reassigned; handler-thread reads are ordered after
        # the start() happens-before edge.
        self._httpd.front = self  # type: ignore[attr-defined]
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="mastic-upload-front", daemon=True)
        self._thread.start()
        self.publish_connections()
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None

    # -- seams the handler threads call ----------------------------

    def _checkpoint(self, step: str) -> None:
        if self.injector is not None:
            self.injector.checkpoint(step)

    def count_request(self, code: int,
                      latency_ms: Optional[float] = None) -> None:
        self.registry.counter("mastic_net_http_requests_total",
                              code=str(code)).inc()
        if latency_ms is not None:
            self.registry.histogram(
                "mastic_net_admission_latency_ms").observe(latency_ms)

    def publish_connections(self) -> None:
        self.registry.gauge("mastic_net_active_connections").set(
            self.controller.active_connections())

    def shed(self, tenant: Optional[str], reason: str) -> None:
        """One front-door refusal into the service's shed ledger
        (tenant-attributed when the path parsed that far)."""
        if tenant is not None:
            self.service.shed_external(tenant, reason)
        else:
            obs_trace.event("shed", tenant="", reason=reason)

    def notify_admitted(self, tenant: str) -> None:
        if self._on_admitted is not None:
            self._on_admitted(tenant)

    # -- the operator-plane request queue --------------------------

    def request_epoch(self, tenant: str) -> bool:
        """Queue one epoch-cut request; False when the bounded
        control queue is full (the handler sheds it, attributed)."""
        with self._control_mu:
            if len(self._epoch_requests) >= self._control_bound:
                return False
            self._epoch_requests.append(tenant)
            return True

    def pop_epoch_requests(self) -> list:
        """Drain the queued cut requests — called by the EMBEDDING
        thread, which owns every `begin_epoch` call."""
        with self._control_mu:
            out = self._epoch_requests
            self._epoch_requests = []
            return out
