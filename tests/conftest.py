"""Test configuration.

Sharding/mesh tests run on a virtual 8-device CPU mesh; the real-TPU
benchmark path is exercised separately by bench.py.  All env vars must
be set before `import jax` (jax snapshots them into config defaults at
import time), hence the ordering below.
"""

import os
import sys

# Force CPU: the ambient environment pins jax to the real TPU tunnel
# (its sitecustomize overrides the jax_platforms *config*, so the env
# var alone is not enough — see the config.update below), and tests
# must not depend on the tunnel — it blocks for minutes when down.
# The virtual 8-device CPU mesh is the test fabric for all sharding
# paths.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = \
        (xla_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402  (after the env setup above, by design)

jax.config.update("jax_platforms", "cpu")
# Persistent compilation cache: REMOVED in r9.  XLA-CPU executables
# serialize, but RELOADING them is unsound in this jaxlib: a process
# that reads a warm cache segfaults mid-run or — strictly worse —
# loads a program that silently computes the wrong thing (observed: a
# round program that rejected every report).  Reproduced on the
# UNMODIFIED pre-r9 tree via a git-worktree A/B (PERF.md §7), so this
# is a fabric deserialization bug, not a property of any one change;
# the "~10x faster reruns" the cache bought are not worth wrong
# crypto.  bench.py / tools/northstar.py now gate the same wiring to
# chip platforms (MASTIC_COMPILE_CACHE forces it); tests always
# compile cold.  Opt back in explicitly at your own risk:
if os.environ.get("MASTIC_COMPILE_CACHE") == "1":
    jax.config.update("jax_compilation_cache_dir",
                      os.environ.get("JAX_COMPILATION_CACHE_DIR",
                                     "/tmp/mastic_tpu_jax_cache"))
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      0.0)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: heavy differential/adversarial/driver suites (excluded "
        "from the fast CI tier; run with -m slow or no filter)")
