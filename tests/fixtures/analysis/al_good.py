"""Known-good: a justified suppression covering a real finding."""


def documented(seed: bytes) -> bytes:
    # mastic-allow: SF001 — fixture: deliberate branch, documented
    if seed[0] & 1:
        return seed[1:]
    return seed
