"""CC002 good fixture: one global acquisition order."""
import threading

_lock_a = threading.Lock()
_lock_b = threading.Lock()


def forward():
    with _lock_a:
        with _lock_b:
            pass


def also_forward():
    with _lock_a:
        with _lock_b:
            pass
