"""Known-good twin of bad_call_arity (lint check 6)."""


def callee(a, b):
    return a + b


def caller():
    return callee(1, b=2)
