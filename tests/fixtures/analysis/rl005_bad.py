"""RL005: the early return skips the wait — the child is left a
zombie."""
import subprocess


def spawn(cmd):
    proc = subprocess.Popen(cmd)
    if not cmd:
        return None
    proc.wait()
