"""Weighted heavy hitters: the multi-round collector loop.

Functionally equivalent to the reference driver
(/root/reference/poc/examples.py:13-91) — per level, aggregate over the
candidate-prefix frontier, threshold-prune, expand survivors — but the
per-report prep loop is replaced by one batched device round per level
(both aggregators' prep + accept + aggregation on device; the FLP
verifier exchange on the weight-check round crosses the host boundary,
as it does between real aggregators).

Thresholds: a dict mapping prefix tuples to ints with a "default" key;
the threshold for a prefix is that of its *longest strict ancestor*
present in the dict, else the default (reference examples.py:26-34,
spec draft-mouris-cfrg-mastic.md:1535-1572).
"""

import os
import time
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..common import gen_rand, vec_add
from ..mastic import Mastic, ReportRejected
from ..metrics import (RoundMetrics, attribute_rejections,
                       count_round_bytes, count_round_ops)
from ..obs import devtime, trace as obs_trace
from ..backend.mastic_jax import BatchedMastic, ReportBatch


def get_reports_from_measurements(mastic: Mastic, ctx: bytes,
                                  measurements: Sequence) -> list:
    """Client side: shard each measurement with fresh randomness."""
    reports = []
    for measurement in measurements:
        nonce = gen_rand(mastic.NONCE_SIZE)
        rand = gen_rand(mastic.RAND_SIZE)
        (public_share, input_shares) = mastic.shard(
            ctx, measurement, nonce, rand)
        reports.append((nonce, public_share, input_shares))
    return reports


def get_threshold(thresholds: dict, prefix: tuple) -> int:
    """Longest-strict-ancestor threshold lookup."""
    for level in reversed(range(len(prefix) - 1)):
        if prefix[:level + 1] in thresholds:
            return thresholds[prefix[:level + 1]]
    return thresholds["default"]


def _vk_array(verify_key: bytes) -> jax.Array:
    return jnp.asarray(np.frombuffer(verify_key, np.uint8))


def _round_fn(bm: BatchedMastic, ctx: bytes, agg_param):
    """The jitted full-round function, cached on the BatchedMastic so
    repeated rounds with the same aggregation parameter (or repeated
    aggregate_by_attribute calls) reuse the compiled program.

    The verify key is a TRACED input, not a baked constant: a fresh
    per-collection key must not recompile the round (it previously
    did — every fresh-key test run re-paid the full XLA compile)."""
    cache = getattr(bm, "_round_cache", None)
    if cache is None:
        cache = {}
        bm._round_cache = cache
    key = (ctx, agg_param)
    fn = cache.get(key)
    if fn is None:
        fn = jax.jit(lambda vk, b: bm.round_device_checks(
            vk, ctx, agg_param, b))
        cache[key] = fn
    return fn


# -- the from-root round through the AOT program tier (ISSUE 10) ------

def root_program_cache(bm: BatchedMastic):
    """The from-root round's ProgramCache, shared per BatchedMastic —
    the artifact tier the attribute-metrics round (and the
    incremental=False differential path) previously sat outside: its
    per-(ctx, agg_param) jits were bare, so every fresh process (and
    every service epoch, which builds a fresh run) re-paid the full
    trace+XLA bill even with a warm artifact store.  Keys ride the
    same runtime+family suffix as eval/agg/wc/rk, so `tools/bake.py
    --attributes` seals them and the service preload at tenant
    admission pulls them in."""
    cache = getattr(bm, "_root_program_cache", None)
    if cache is None:
        from . import artifacts
        from .pipeline import ProgramCache

        cache = ProgramCache(store=artifacts.store_from_env())
        bm._root_program_cache = cache
    return cache


def root_program_key(bm: BatchedMastic, ctx: bytes, agg_param,
                     rows: int, shards: int = 0) -> tuple:
    """Shape-and-parameter key for one from-root round program.  The
    candidate prefixes are BAKED into the traced program (they drive
    the gather schedule), so the key carries their digest — two
    attribute sets of equal size map to different keys, never to each
    other's executable."""
    import hashlib

    from . import artifacts

    (level, prefixes, do_weight_check) = agg_param
    packed = "|".join("".join("1" if b else "0" for b in p)
                      for p in prefixes).encode()
    digest = hashlib.sha256(packed).hexdigest()[:16]
    return ("root", rows, shards, level, int(do_weight_check),
            digest, artifacts.runtime_tag(),
            artifacts.family_id(bm, ctx))


def root_round_program(bm: BatchedMastic, ctx: bytes, agg_param,
                       args: tuple, mesh=None) -> tuple:
    """(program, wait_seconds) for a from-root round at the shapes of
    `args` — the in-process tier first, the digest-sealed artifact
    store below it, inline XLA last (attributed in the cache stats,
    surfaced per round in `extra["artifacts"]`)."""
    from .pipeline import to_struct

    if mesh is not None:
        from ..drivers.attribute_metrics import _round_fn_masked

        fn = _round_fn_masked(bm, ctx, agg_param, mesh)
        shards = mesh.shape["reports"]
    else:
        fn = _round_fn(bm, ctx, agg_param)
        shards = 0
    rows = int(args[1].nonces.shape[0])
    key = root_program_key(bm, ctx, agg_param, rows, shards)
    structs = jax.tree_util.tree_map(to_struct, args)
    return root_program_cache(bm).get(
        key, lambda: fn.lower(*structs))


def _artifacts_delta(cache, mark: dict) -> dict:
    """The per-round `extra["artifacts"]` block from a ProgramCache
    stats snapshot taken at round start (obs/schema.py shape)."""
    s = cache.stats
    return {
        "store": (cache.store.path if cache.store is not None
                  else None),
        "hits": s["artifact_hits"] - mark["artifact_hits"],
        "inline_compiles": (s["inline_compiles"]
                            - mark["inline_compiles"]),
        "load_ms": round(s["artifact_load_ms"]
                         - mark["artifact_load_ms"], 2),
    }


def run_round_stage(bm: BatchedMastic, verify_key: bytes, ctx: bytes,
                    agg_param, batch: ReportBatch) -> dict:
    """Dispatch one from-root round WITHOUT blocking: program fetch
    (AOT tier), async dispatch, futures into the handle.  The paired
    `run_round_collect` issues the round's single blocking sync — the
    seam the overlapped epoch executor interleaves across tenants
    (tenant B stages here while tenant A's dispatched round computes
    on device)."""
    from .pipeline import paused_gc

    cache = root_program_cache(bm)
    mark = dict(cache.stats)
    args = (_vk_array(verify_key), batch)
    with paused_gc():
        (prog, wait_s) = root_round_program(bm, ctx, agg_param, args)
        out = prog(*args)
    return {"out": out, "compile_wait_s": wait_s,
            "artifacts": _artifacts_delta(cache, mark)}


def run_round_collect(bm: BatchedMastic, verify_key: bytes,
                      ctx: bytes, agg_param, handle: dict,
                      reports: Optional[list] = None,
                      accept_out: Optional[list] = None,
                      metrics_out: Optional[list] = None) -> list:
    """The blocking half of `run_round_stage`: one sync, downloads,
    the scalar-fallback splice, metrics, unshard."""
    from ..backend.schedule import LevelSchedule

    (level, prefixes, _do_weight_check) = agg_param
    (agg0, agg1, accept, ok, checks) = handle["out"]
    jax.block_until_ready((agg0, agg1, accept, ok))
    accept = np.asarray(accept).copy()
    ok = np.asarray(ok)
    sched = LevelSchedule(prefixes, level, bm.m.vidpf.BITS)
    agg_shares = [bm.agg_share_to_host(a) for a in (agg0, agg1)]
    extra = {"artifacts": handle["artifacts"]}
    result = finalize_round(
        bm, verify_key, ctx, agg_param, reports, ok, accept,
        {k: np.asarray(v) for (k, v) in checks.items()}, agg_shares,
        padded_width=sched.total_nodes,
        nodes_evaluated=sched.total_nodes, metrics_out=metrics_out,
        extra=extra)
    if accept_out is not None:
        accept_out.append(accept)
    return result


def run_round(bm: BatchedMastic, verify_key: bytes, ctx: bytes,
              agg_param, batch: ReportBatch,
              reports: Optional[list] = None,
              accept_out: Optional[list] = None,
              metrics_out: Optional[list] = None) -> list:
    """One aggregation round on the batched backend: both preps, all
    checks (incl. the device FLP on weight-check rounds), masked
    aggregation, unshard.  Returns the per-prefix aggregate result;
    appends the accept mask to `accept_out` and a RoundMetrics record
    to `metrics_out`.

    `reports` is the host-side report list backing `batch`; it is only
    touched when XOF rejection sampling fires for some lane (the scalar
    fallback, see `splice_rejected`).  Since ISSUE 10 the round
    program rides the AOT artifact tier (`root_round_program`), and
    the round itself is the stage/collect pair the overlapped epoch
    executor splits."""
    handle = run_round_stage(bm, verify_key, ctx, agg_param, batch)
    return run_round_collect(bm, verify_key, ctx, agg_param, handle,
                             reports=reports, accept_out=accept_out,
                             metrics_out=metrics_out)


def finalize_round(bm: BatchedMastic, verify_key: bytes, ctx: bytes,
                   agg_param, reports: Optional[list],
                   ok: np.ndarray, accept: np.ndarray, checks: dict,
                   agg_shares: list, padded_width: int,
                   nodes_evaluated: int,
                   metrics_out: Optional[list],
                   extra: Optional[dict] = None) -> list:
    """Shared from-root round finalization (run_round and the chunked
    attribute-metrics round): metrics record with per-check rejection
    attribution, the XOF-rejection scalar-fallback splice, unshard.

    From-root rounds evaluate the whole child grid; the beta shares
    on weight-check rounds reuse the depth-0 children (contrast the
    reference, whose get_beta_share re-evaluates them,
    mastic.py:235-236)."""
    (level, prefixes, _do_weight_check) = agg_param
    num_reports = accept.shape[0]
    metrics = RoundMetrics(level=level, frontier_width=len(prefixes),
                           padded_width=padded_width,
                           reports_total=num_reports)
    attribute_rejections(metrics, checks["eval_proof"],
                         checks.get("weight_check"),
                         checks.get("joint_rand"), device_ok=ok)
    count_round_ops(metrics, bm.m, num_reports, nodes_evaluated,
                    include_key_setup=True)
    count_round_bytes(metrics, bm.m, agg_param, num_reports)
    metrics.xof_fallbacks = int((~ok).sum())
    if extra:
        metrics.extra.update(extra)

    splice_rejected(bm.m, verify_key, ctx, agg_param, reports,
                    ok, accept, agg_shares)
    metrics.accepted = int(accept.sum())
    metrics.rejected_fallback = int((~ok & ~accept).sum())
    if metrics_out is not None:
        metrics_out.append(metrics)
    return bm.m.unshard(agg_param, agg_shares, int(accept.sum()))


def scalar_round_out_shares(m: Mastic, verify_key: bytes, ctx: bytes,
                            agg_param, report) -> Optional[list]:
    """One report through the scalar protocol round (both preps, the
    prep-share exchange, prep_next).  Returns the two out shares, or
    None if the report is rejected by the checks.

    The scalar layer's XOF sampler implements the true rejection loop
    (vdaf-13 §6.2; reference consumption /root/reference/poc/
    vidpf.py:352-364), so this path is exact for the lanes the batched
    sampler flags."""
    (nonce, public_share, input_shares) = report
    states = []
    shares = []
    for agg_id in range(2):
        (state, share) = m.prep_init(verify_key, ctx, agg_id, agg_param,
                                     nonce, public_share,
                                     input_shares[agg_id])
        states.append(state)
        shares.append(share)
    try:
        prep_msg = m.prep_shares_to_prep(ctx, agg_param, shares)
        return [m.prep_next(ctx, state, prep_msg) for state in states]
    except ReportRejected:
        return None


def splice_rejected(m: Mastic, verify_key: bytes, ctx: bytes, agg_param,
                    reports: Optional[list], ok: np.ndarray,
                    accept: np.ndarray, agg_shares: list) -> None:
    """The XOF rejection-sampling fallback (vdaf-13 §6.2).

    Lanes where `ok` is False sampled a field element outside the
    field (~2^-32 per element for Field64): their device results are
    garbage, and the device aggregates already exclude them.  Recompute
    exactly those reports through the scalar layer and splice their
    out shares and accept bits into the round's host-side results
    (`accept` and `agg_shares` are mutated in place)."""
    if ok.all():
        return
    if reports is None:
        raise ValueError(
            "XOF rejection sampling fired but the host reports needed "
            "for the scalar fallback were not provided")
    for r in np.flatnonzero(~ok):
        out_shares = scalar_round_out_shares(m, verify_key, ctx,
                                             agg_param, reports[r])
        accept[r] = out_shares is not None
        if out_shares is not None:
            for a in range(2):
                agg_shares[a] = vec_add(agg_shares[a], out_shares[a])


def compute_heavy_hitters(mastic: Mastic, ctx: bytes, thresholds: dict,
                          reports: list,
                          verify_key: Optional[bytes] = None,
                          incremental: bool = True) -> list:
    """The full collector loop (reference examples.py:37-91).

    With `incremental` (the default), each aggregator carries its
    prefix-tree state across rounds and only evaluates the new level's
    frontier — O(BITS * frontier) node evaluations for the whole run
    instead of O(BITS^2 * frontier) — using one compiled round program
    per padded frontier width (backend/incremental.py).  The
    `incremental=False` path re-evaluates from the root each round
    (one compile per level) and serves as the differential reference.
    """
    run = HeavyHittersRun(mastic, ctx, thresholds, reports,
                          verify_key=verify_key,
                          incremental=incremental)
    while run.step():
        pass
    return run.result()


# v2 added the chunk_size meta field (0 = unchunked); v3 added the
# per-depth creation layouts (carries are no longer compacted per
# round, so the row arrangement can't be derived from the last
# aggregation parameter alone).  v1/v2 checkpoints hold compacted
# carries, whose arrangement IS needed_paths(last prefixes) — still
# restorable.
_CKPT_VERSION = 3


def _ckpt_binding(verify_key: bytes, ctx: bytes,
                  thresholds: dict) -> np.ndarray:
    """Digest binding a checkpoint to its (verify_key, ctx,
    thresholds): restoring under a different key/context would
    silently reject every report (the carries were derived under the
    old key), and different thresholds would prune a different
    frontier — make either mismatch loud instead."""
    import hashlib
    thresh_repr = repr(sorted(thresholds.items(), key=repr)).encode()
    digest = hashlib.sha256(
        len(verify_key).to_bytes(2, "little") + verify_key +
        len(ctx).to_bytes(2, "little") + ctx + thresh_repr
    ).digest()
    return np.frombuffer(digest, np.uint8)


def _paths_to_array(paths) -> np.ndarray:
    if not paths:
        return np.zeros((0, 0), bool)
    return np.array([[bool(b) for b in p] for p in paths], bool)


def _paths_from_array(arr) -> list:
    return [tuple(bool(x) for x in row) for row in np.asarray(arr)]


class HeavyHittersRun:
    """A resumable heavy-hitters collection run: one `step()` per tree
    level, checkpointable between levels (SURVEY.md §5; the state the
    reference would persist is named at examples.py:48,75 plus the
    cache-across-rounds tree, vidpf.py:243-245).

    `to_bytes()` serializes the collector state and both aggregators'
    incremental carries; `from_bytes()` restores a run that continues
    bit-identically.  The report store itself is the caller's to
    persist (a real deployment keeps uploads in a database); the
    checkpoint covers everything derived from them.
    """

    def __init__(self, mastic: Mastic, ctx: bytes, thresholds: dict,
                 reports: Optional[list],
                 verify_key: Optional[bytes] = None,
                 incremental: bool = True,
                 chunk_size: Optional[int] = None,
                 store=None, mesh=None, batch=None):
        from .chunked import ChunkedIncrementalRunner, HostReportStore

        if verify_key is None:
            verify_key = gen_rand(mastic.VERIFY_KEY_SIZE)
        self.mastic = mastic
        self.ctx = ctx
        self.thresholds = thresholds
        self.reports = reports
        self.verify_key = verify_key
        self.bm = BatchedMastic(mastic)
        # `batch` lets a device-batched client pipeline (e.g.
        # tools/northstar.py's shard_device loop) hand over marshalled
        # arrays directly — at fleet scale there is no scalar report
        # list to marshal (the scalar `reports` stays optional and is
        # only needed by the XOF-rejection fallback).
        if chunk_size is not None or store is not None:
            # At-scale path: reports stream through the device chunk
            # by chunk; the device never holds the whole batch.
            if store is None:
                store = HostReportStore.from_batch(
                    batch if batch is not None
                    else self.bm.marshal_reports(reports), chunk_size)
            self.store = store
            self.batch = None
            self.num_reports = store.num_reports
            self.runner = ChunkedIncrementalRunner(
                self.bm, verify_key, ctx, store, reports, mesh=mesh)
        else:
            self.store = None
            self.batch = (batch if batch is not None
                          else self.bm.marshal_reports(reports))
            self.num_reports = int(self.batch.nonces.shape[0])
            self.runner = (
                _IncrementalRunner(self.bm, verify_key, ctx, self.batch,
                                   reports)
                if incremental else None)
        if mesh is not None:
            if self.runner is None:
                raise ValueError(
                    "mesh sharding requires the incremental runner "
                    "(incremental=True or a chunk_size/store)")
            from ..parallel.mesh import shard_incremental_runner
            shard_incremental_runner(self.runner, mesh)
        self.level = 0
        self.prefixes: list = [(False,), (True,)]
        self.prev_agg_params: list = []
        self.heavy_hitters: list = []
        self.metrics: list = []  # one RoundMetrics per completed level
        self.profile_dir: Optional[str] = None  # jax.profiler target
        self.obs_tenant = ""     # telemetry label (set by the service)
        self.done = False

    def step(self) -> bool:
        """Run one level's aggregation round.  Returns True while more
        rounds remain.

        Telemetry (ISSUE 7): each round runs inside a "round" trace
        span (attrs: tenant/round/level/frontier_width/reports; chunk
        spans nest under it) and feeds the chunk-phase histograms +
        compile-vs-execute attribution (obs/devtime.observe_round).
        Profiling: when `self.profile_dir` is set (a directory path)
        — or once per process when `MASTIC_JAX_PROFILE=dir` is armed
        — the round executes under jax.profiler.trace; open the
        result with TensorBoard / xprof.  Per-round wall-clock always
        lands in metrics.extra["round_wall_ms"].

        ISSUE 10: `step()` is the `step_begin` / `step_finish` pair
        run back to back.  The overlapped epoch executor calls the
        halves split across tenants — begin dispatches this level's
        round without blocking, finish issues the one blocking sync
        and advances the frontier."""
        handle = self.step_begin()
        if handle is None:
            return False
        return self.step_finish(handle)

    def step_begin(self) -> Optional[dict]:
        """Dispatch one level's round without blocking (resident
        runner) or run it outright (chunked / from-root, where the
        intra-round pipeline owns the sync discipline — the handle's
        ``atomic`` flag says which happened).  Returns None when no
        rounds remain.  Every handle MUST be passed to `step_finish`
        — the frontier only advances there."""
        if self.done:
            return None
        if not self.prefixes:
            self.done = True
            return None
        level = self.level
        agg_param = (level, tuple(self.prefixes), level == 0)
        assert self.mastic.is_valid(agg_param, self.prev_agg_params)
        profile_dir = self.profile_dir or devtime.take_profile_dir()
        prof = (jax.profiler.trace(profile_dir)
                if profile_dir else None)
        tracer = obs_trace.get_tracer()
        span = tracer.start_detached_span(
            "round", tenant=self.obs_tenant, round=level,
            level=level, frontier_width=len(self.prefixes),
            reports=self.num_reports, profiled=bool(profile_dir))
        handle = {"agg_param": agg_param, "span": span, "prof": prof,
                  "t0": time.perf_counter(), "atomic": True,
                  "rh": None, "result": None}
        if prof is not None:
            prof.__enter__()
        try:
            with tracer.use_parent(span):
                if isinstance(self.runner, _IncrementalRunner):
                    # The resident round splits at the sync seam: the
                    # handle holds in-flight futures, finish() blocks.
                    handle["rh"] = self.runner.round_stage(agg_param)
                    handle["atomic"] = False
                elif self.runner is not None:
                    handle["result"] = self.runner.round(
                        agg_param, metrics_out=self.metrics)
                else:
                    handle["result"] = run_round(
                        self.bm, self.verify_key, self.ctx,
                        agg_param, self.batch, self.reports,
                        metrics_out=self.metrics)
        except BaseException as exc:
            self._step_cleanup(handle, error=exc)
            raise
        return handle

    def step_finish(self, handle: dict) -> bool:
        """Collect the staged round (blocking sync for a split
        handle), stamp its metrics, and advance the frontier.
        Returns True while more rounds remain."""
        tracer = obs_trace.get_tracer()
        try:
            if not handle["atomic"]:
                with tracer.use_parent(handle["span"]):
                    handle["result"] = self.runner.round_collect(
                        handle["rh"], metrics_out=self.metrics)
        except BaseException as exc:
            self._step_cleanup(handle, error=exc)
            raise
        agg_result = handle["result"]
        self._step_cleanup(handle)
        if self.metrics:
            self.metrics[-1].extra["round_wall_ms"] = round(
                (time.perf_counter() - handle["t0"]) * 1e3, 2)
            self.metrics[-1].validate_extra()
            devtime.observe_round(self.metrics[-1],
                                  tenant=self.obs_tenant)
        (level, _prefixes, _wc) = handle["agg_param"]
        self.prev_agg_params.append(handle["agg_param"])

        survivors = [
            prefix for (prefix, count) in zip(self.prefixes, agg_result)
            if count >= get_threshold(self.thresholds, prefix)
        ]
        if level < self.mastic.vidpf.BITS - 1:
            self.prefixes = [p + (bit,) for p in survivors
                             for bit in (False, True)]
        else:
            self.heavy_hitters = survivors
        self.level += 1
        if self.level >= self.mastic.vidpf.BITS or not self.prefixes:
            self.done = True
        return not self.done

    def _step_cleanup(self, handle: dict, error=None) -> None:
        """Close the round's profiler bracket and trace span exactly
        once (both halves may hit an exception path)."""
        prof = handle.pop("prof", None)
        if prof is not None:
            prof.__exit__(None, None, None)
        span = handle.pop("span", None)
        if span is not None:
            if error is not None:
                span.set_default("error", type(error).__name__)
            obs_trace.get_tracer().end_span(span)

    def result(self) -> list:
        return self.heavy_hitters

    def frontier(self) -> list:
        """The truncated-but-correct output after the last COMPLETED
        level (the collector service's deadline-degradation contract,
        drivers/service.py): the prefixes that passed every completed
        round's threshold.  A finished run's frontier IS its result;
        a run cut off mid-tree reports the survivors of the last
        completed level (recovered as the unique parents of the
        expanded candidate set — step() expands survivors into their
        children before returning).  Nothing is claimed about levels
        that never ran."""
        if self.done:
            return list(self.heavy_hitters)
        if self.level == 0:
            return []   # no round completed: nothing verified yet
        seen: dict = {}
        for p in self.prefixes:
            seen.setdefault(p[:-1], None)
        return list(seen)

    def rounds_completed(self) -> int:
        """Levels completed over the run's lifetime (survives
        checkpoint-resume; `metrics` only covers this process)."""
        return self.level

    # -- checkpoint / resume ---------------------------------------

    def to_bytes(self) -> bytes:
        """Serialize the run between levels (collector state + both
        carries + the rejection-fallback mask)."""
        import io

        from ..backend.incremental import carry_to_arrays
        from .chunked import ChunkedIncrementalRunner

        chunked = isinstance(self.runner, ChunkedIncrementalRunner)
        num_layouts = (len(self.runner.layouts)
                       if self.runner is not None else 0)
        data = {
            "meta": np.array(
                [_CKPT_VERSION, self.level, int(self.done),
                 0 if self.runner is None else 1,
                 self.mastic.vidpf.BITS, self.num_reports,
                 self.store.chunk_size if chunked else 0,
                 num_layouts], np.int64),
            "binding": _ckpt_binding(self.verify_key, self.ctx,
                                     self.thresholds),
            "prefixes": _paths_to_array(self.prefixes),
            "heavy_hitters": _paths_to_array(self.heavy_hitters),
            "prev_levels": np.array(
                [p[0] for p in self.prev_agg_params], np.int64),
            "prev_wc": np.array(
                [p[2] for p in self.prev_agg_params], bool),
        }
        if self.prev_agg_params:
            data["last_prefixes"] = _paths_to_array(
                self.prev_agg_params[-1][1])
        for d in range(num_layouts):
            data[f"layout_{d}"] = _paths_to_array(
                self.runner.layouts[d])
        if chunked:
            data["width"] = np.int64(self.runner.width)
            data["fallback"] = self.runner.fallback
            data.update(self.runner.state_arrays())
        elif self.runner is not None:
            data["width"] = np.int64(self.runner.width)
            data["fallback"] = self.runner.fallback
            data.update(carry_to_arrays(self.runner.carries[0], "c0_"))
            data.update(carry_to_arrays(self.runner.carries[1], "c1_"))
        buf = io.BytesIO()
        np.savez(buf, **data)
        return buf.getvalue()

    @classmethod
    def from_bytes(cls, mastic: Mastic, ctx: bytes, thresholds: dict,
                   reports: Optional[list], verify_key: bytes,
                   data: bytes, store=None,
                   mesh=None, batch=None) -> "HeavyHittersRun":
        """Restore a checkpointed run over the same report store (a
        chunked run may pass `store` instead of scalar reports; a
        resident run built from a marshalled `batch` passes the same
        batch back — there is no scalar list at fleet scale)."""
        import io

        from ..backend.incremental import (carry_from_arrays,
                                           needed_paths)
        from .chunked import ChunkedIncrementalRunner

        arrays = np.load(io.BytesIO(data), allow_pickle=False)
        meta = [int(x) for x in arrays["meta"]]
        version = meta[0]
        num_layouts = 0
        if version == 1:
            (_, level, done, incremental, bits, num_reports) = meta
            chunk_size = 0
        elif version == 2:
            (_, level, done, incremental, bits, num_reports,
             chunk_size) = meta
        elif version == _CKPT_VERSION:
            (_, level, done, incremental, bits, num_reports,
             chunk_size, num_layouts) = meta
        else:
            raise ValueError(f"unknown checkpoint version {version}")
        if chunk_size == 0 and store is not None:
            # Passing a store would silently build the OTHER runner
            # kind and die on (or worse, skip) the missing per-chunk
            # carry arrays — refuse descriptively instead.
            raise ValueError(
                "checkpoint was taken by the resident (unchunked) "
                "runner; restore it with scalar reports, not a store")
        if chunk_size and store is None and reports is None:
            raise ValueError(
                "chunked checkpoint needs its report store (or the "
                "scalar reports to rebuild one)")
        if chunk_size == 0 and reports is None and batch is None:
            raise ValueError(
                "resident checkpoint needs the scalar reports (or the "
                "marshalled batch) it was taken over")
        restored_n = (store.num_reports if store is not None
                      else int(batch.nonces.shape[0])
                      if batch is not None else len(reports))
        if bits != mastic.vidpf.BITS or num_reports != restored_n:
            raise ValueError("checkpoint does not match this "
                             "instantiation / report store")
        if chunk_size and store is not None \
                and store.chunk_size != chunk_size:
            raise ValueError(
                f"checkpoint was taken with chunk_size={chunk_size}, "
                f"store has {store.chunk_size}")
        if not np.array_equal(np.asarray(arrays["binding"]),
                              _ckpt_binding(verify_key, ctx,
                                            thresholds)):
            raise ValueError("checkpoint was taken under a different "
                             "verify_key / ctx / thresholds")

        run = cls(mastic, ctx, thresholds, reports,
                  verify_key=verify_key, incremental=bool(incremental),
                  chunk_size=chunk_size if chunk_size else None,
                  store=store, mesh=mesh, batch=batch)
        run.level = level
        run.done = bool(done)
        run.prefixes = _paths_from_array(arrays["prefixes"])
        run.heavy_hitters = _paths_from_array(arrays["heavy_hitters"])
        prev_levels = [int(x) for x in arrays["prev_levels"]]
        prev_wc = [bool(x) for x in arrays["prev_wc"]]
        last_prefixes: tuple = ()
        if prev_levels:
            last_prefixes = tuple(
                _paths_from_array(arrays["last_prefixes"]))
        # is_valid consumes only the weight-check flags and the last
        # level; the last round's prefixes are kept exactly because
        # they also determine the runner's carried paths.
        run.prev_agg_params = [
            (lvl, last_prefixes if i == len(prev_levels) - 1 else (),
             wc)
            for (i, (lvl, wc)) in enumerate(zip(prev_levels, prev_wc))
        ]
        def restored_layouts():
            """v3 saves the creation layouts; v1/v2 carries were
            compacted every round, so their arrangement equals the
            needed-paths of the last aggregation parameter."""
            if version >= 3:
                return [
                    _paths_from_array(arrays[f"layout_{d}"])
                    for d in range(num_layouts)
                ]
            return needed_paths(last_prefixes, prev_levels[-1])

        if isinstance(run.runner, ChunkedIncrementalRunner) \
                and prev_levels:
            from ..backend.incremental import IncrementalMastic
            from .chunked import check_envelope

            runner = run.runner
            width = int(arrays["width"])
            if width != runner.width:
                # A checkpoint taken at a grown width must re-clear
                # the envelope on the restoring host/chip — adopting
                # it unchecked would OOM with a raw allocator error
                # instead of the guard's refusal.
                check_envelope(runner.bm, runner.store.chunk_size,
                               width, runner.num_reports,
                               runner.n_device_shards)
                runner.width = width
                runner.engine = IncrementalMastic(runner.bm, width)
                runner._eval_fn = None
                runner._combine_fn = None
            runner.fallback = np.asarray(arrays["fallback"], bool)
            runner.load_state(arrays, runner.store.num_chunks)
            runner.layouts = restored_layouts()
        elif run.runner is not None and prev_levels:
            from ..backend.incremental import IncrementalMastic

            runner = run.runner
            width = int(arrays["width"])
            if width != runner.width:
                # Re-point the engine at the stored width directly —
                # the freshly-initialized carries are about to be
                # replaced wholesale, so _grow's padding would be
                # wasted device work.
                runner.width = width
                runner.engine = IncrementalMastic(runner.bm, width)
                runner._eval_fn = None
                runner._combine_fn = None
            runner.fallback = np.asarray(arrays["fallback"], bool)
            runner.carries = [
                carry_from_arrays(arrays, "c0_"),
                carry_from_arrays(arrays, "c1_"),
            ]
            if runner.mesh is not None:
                from ..parallel.mesh import place_reports
                runner.carries = [place_reports(runner.mesh, c)
                                  for c in runner.carries]
            runner.layouts = restored_layouts()
        return run


class RoundPrograms:
    """Shared round-program machinery for the incremental runners.

    The resident (_IncrementalRunner) and chunked
    (drivers/chunked.ChunkedIncrementalRunner) runners execute the
    identical round program — one definition keeps their semantics
    locked together.  Subclasses provide bm / verify_key / ctx /
    engine / width / layouts / mesh and a _grow(width), and call
    _init_programs() from __init__.

    Two program tiers:

    * `_eval_jit` / `_combine_jit` — the jitted functions (the mesh
      path calls them directly: GSPMD needs jit's sharding
      propagation);
    * `self.programs` (drivers/pipeline.ProgramCache) — ahead-of-time
      compiled executables keyed by the shapes each round actually
      closes over (chunk rows, padded width, the pow2 binder/out
      buckets).  Shape-keying makes width growth safe by
      construction: a grown round's key differs, so no invalidation
      step can be forgotten (the r5..r8 code cleared `_eval_fn` /
      `_agg_fn` on _grow but left `_wc_fns` — benign only because
      the weight-check program's input shapes happen to be
      width-independent; tests/test_pipeline.py locks the
      grow-then-weight-check path either way).  `_warm_next`
      compiles the predicted next level's programs while the current
      round's dispatched device work is still executing (PERF.md:
      the measured ~100 s of inline compile in the production
      round); see ProgramCache for why this is synchronous rather
      than a compiler thread.
    """

    def _init_programs(self) -> None:
        from . import artifacts
        from .pipeline import ProgramCache

        self._eval_fn = None
        self._combine_fn = None
        self._wc_fns: dict = {}
        self._rk_fn_jit = None
        # Runtime + family suffix on every program key: a program
        # compiled under a different jax build/backend, or for a
        # different instantiation/ctx, can never be served — in
        # process (ProgramCache refuses skewed runtimes) or from the
        # AOT artifact store (drivers/artifacts.py, ROADMAP item 4).
        self._key_suffix = (artifacts.runtime_tag(),
                            artifacts.family_id(self.bm, self.ctx))
        self.programs = ProgramCache(store=artifacts.store_from_env())
        self._warmed_keys: set = set()
        self._stats_mark = dict(self.programs.stats)

    # -- mesh plumbing (report-axis data parallelism) --------------

    def _mesh_shards(self) -> int:
        """Report-axis size of the installed mesh (0 = no mesh) — part
        of every program-cache key, so a grown-or-resharded runner maps
        to fresh keys instead of replaying a mismatched executable."""
        return (self.mesh.shape["reports"] if self.mesh is not None
                else 0)

    def _rep_sharding(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self.mesh, P("reports"))

    def _repl_sharding(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self.mesh, P())

    # Whether the eval program donates its carry args.  True for the
    # runners (in-process compiles: donation halves the transient
    # carry footprint).  artifacts.make_baker sets False: an
    # executable with input-output aliasing DOUBLE-FREES its donated
    # buffers when deserialized on this jaxlib CPU (heap corruption,
    # allocator-state dependent, invisible to the output probe —
    # PERF.md §11), so baked programs must be donation-free and
    # ArtifactStore.save refuses donating executables outright.
    _donate_carries = True

    def _donation_safe(self) -> bool:
        """Donation is only safe when the executable can never come
        back DESERIALIZED.  The artifact store enforces that by
        refusing donating executables (PERF.md §11), but jax's own
        persistent compilation cache (JAX_COMPILATION_CACHE_DIR)
        deserializes jitted executables on a hit behind our back —
        same double-free, different loader.  Observed live in the WAL
        kill-9 drill: a restarted collector whose level-0 eval came
        from the warm shared cache corrupted the heap, and the FLP
        weight check then rejected every report (or segfaulted at
        teardown) while a cold-compiling child never failed.  So:
        drop donation whenever the persistent cache is configured."""
        if not self._donate_carries:
            return False
        cache_dir = (getattr(jax.config, "jax_compilation_cache_dir",
                             None)
                     or os.environ.get("JAX_COMPILATION_CACHE_DIR"))
        return not cache_dir

    def _eval_jit(self):
        if self._eval_fn is None:
            engine = self.engine
            ctx = self.ctx

            def both(vk, c0, c1, rnd, ext_rk, conv_rk, cws):
                (c0, proof0, out0, ok0) = engine.agg_round(
                    0, vk, ctx, c0, rnd, ext_rk, conv_rk, cws)
                (c1, proof1, out1, ok1) = engine.agg_round(
                    1, vk, ctx, c1, rnd, ext_rk, conv_rk, cws)
                accept = jnp.all(proof0 == proof1, axis=-1)
                return (c0, c1, out0, out1, accept, ok0 & ok1)

            # Carries are donated (unless _donate_carries is off, the
            # bake path): both runners replace them with the outputs
            # (resident keeps them resident; chunked re-uploads fresh
            # buffers every chunk).  The verify key is traced so a
            # fresh per-collection key reuses the compiled program.
            # Under a mesh every output is pinned report-sharded so the
            # eval -> combine handoff has deterministic shardings (the
            # AOT warm lowers against exactly these).
            kwargs: dict = {}
            if self._donation_safe():
                kwargs["donate_argnums"] = (1, 2)
            if self.mesh is not None:
                rep = self._rep_sharding()
                kwargs["out_shardings"] = (rep,) * 6
            self._eval_fn = jax.jit(both, **kwargs)
        return self._eval_fn

    def _combine_jit(self):
        """Accept-mask combine + masked aggregation, fully on device:
        the pipelined round's replacement for the host-side boolean
        folds that forced a blocking `np.asarray` wall between the
        tree step and the aggregate.  Rounds without a weight check
        pass all-ones for the three wc masks, so one program
        signature serves every level-kind; limb arithmetic is exact
        modular integer math, so the fused masked sum is bit-equal to
        the old standalone aggregate."""
        if self._combine_fn is None:
            bm = self.bm

            def combine(out0, out1, accept_eval, ok, valid,
                        wc_accept, wc_ok, jr):
                accept = (accept_eval & ok & valid
                          & wc_accept & wc_ok & jr)
                return (accept, bm.aggregate(out0, accept),
                        bm.aggregate(out1, accept))

            kwargs: dict = {}
            if self.mesh is not None:
                # The masked sum over the report-sharded axis is THE
                # round's only cross-chip collective: GSPMD lowers it
                # to per-shard partial sums + a psum over ICI, and the
                # replicated output sharding makes that explicit.
                # Field addition is exact modular integer math, so the
                # shard-then-psum order is bit-identical to the serial
                # single-device sum.
                kwargs["out_shardings"] = (
                    self._rep_sharding(), self._repl_sharding(),
                    self._repl_sharding())
            self._combine_fn = jax.jit(combine, **kwargs)
        return self._combine_fn

    # -- shape-keyed AOT programs (drivers/pipeline.py) ------------

    def _eval_key(self, rows: int, plan) -> tuple:
        from .pipeline import plan_shape_key

        return ("eval", rows, self._mesh_shards()) \
            + plan_shape_key(plan) + self._key_suffix

    def _agg_key(self, rows: int, out_cols: int) -> tuple:
        return ("agg", rows, self._mesh_shards(), out_cols) \
            + self._key_suffix

    def _wc_key(self, rows: int, level: int) -> tuple:
        return ("wc", rows, self._mesh_shards(), level) \
            + self._key_suffix

    def _rk_key(self, rows: int) -> tuple:
        # The AES key-schedule program runs before mesh placement on
        # every path, so the mesh shape is not part of its key.
        return ("rk", rows) + self._key_suffix

    def _preload_first_round(self, rows: int, rk_rows: int) -> int:
        """Pull the FIRST round's program set (level-0 eval/agg/wc +
        the key schedule) from the artifact store at construction —
        exactly the keys on the time-to-first-round critical path.
        Deeper levels prefetch in the predictor's overlapped warm
        slot instead (`ProgramCache.warm` consults the store before
        compiling), so their ~1.5 s-per-program load latency hides
        behind device execution rather than stacking up in front of
        round 0 (measured: whole-family preload put 10 sequential
        loads on the critical path and more than doubled the warm
        cold start)."""
        if self.programs.store is None:
            return 0
        from ..backend.incremental import RoundPlan

        plan0 = RoundPlan(((False,), (True,)), 0,
                          self.bm.m.vidpf.BITS, self.width, [])
        out_cols = len(plan0.out_idx) * (1 + self.bm.m.flp.OUTPUT_LEN)
        wanted = {self._eval_key(rows, plan0),
                  self._agg_key(rows, out_cols),
                  self._wc_key(rows, 0),
                  self._rk_key(rk_rows)}
        return self.programs.preload(lambda key: key in wanted)

    def _artifacts_block(self) -> dict:
        """The per-round `extra["artifacts"]` record (obs/schema.py):
        artifact hits vs inline compiles since the previous round —
        the stamp that makes "this round never traced" a measured
        claim in every metrics record."""
        s = self.programs.stats
        m = self._stats_mark
        block = {
            "store": (self.programs.store.path
                      if self.programs.store is not None else None),
            "hits": s["artifact_hits"] - m["artifact_hits"],
            "inline_compiles": (s["inline_compiles"]
                                - m["inline_compiles"]),
            "warm_compiles": (s["warm_compiles"]
                              - m["warm_compiles"]),
            "load_ms": round(s["artifact_load_ms"]
                             - m["artifact_load_ms"], 2),
        }
        self._stats_mark = dict(s)
        return block

    def _eval_program(self, rows: int, plan, args) -> tuple:
        """(program, compile_wait_seconds) for this round's eval:
        the cached AOT executable, compiled inline only when
        prediction missed.  Mesh rounds use the same path — lowering
        from the concretely placed args bakes their NamedShardings
        into the program (and the cache key carries the mesh shape),
        so steady-state sharded rounds are zero-inline-compile too."""
        return self.programs.get(
            self._eval_key(rows, plan),
            lambda: self._eval_jit().lower(*args))

    def _agg_program(self, rows: int, cargs) -> tuple:
        return self.programs.get(
            self._agg_key(rows, cargs[0].shape[1]),
            lambda: self._combine_jit().lower(*cargs))

    def _wc_program(self, rows: int, level: int, wcargs) -> tuple:
        """The weight-check (FLP) program through the same AOT cache
        tier as eval/agg: pre-r14 it was a bare per-level jit, so a
        cold process's FIRST round (level 0 runs the weight check)
        paid its full compile outside the artifact machinery."""
        return self.programs.get(
            self._wc_key(rows, level),
            lambda: self._wc_fn(level).lower(*wcargs))

    def _rk_jit(self):
        if self._rk_fn_jit is None:
            (bm, ctx) = (self.bm, self.ctx)
            self._rk_fn_jit = jax.jit(
                lambda n: bm.vidpf.roundkeys(ctx, n))
        return self._rk_fn_jit

    def _rk_program(self, rows: int, args) -> tuple:
        """The AES round-key schedule, AOT-cached: both runners pay
        it once at construction — the last compile standing between a
        warm artifact store and a trace-free cold start."""
        return self.programs.get(
            self._rk_key(rows),
            lambda: self._rk_jit().lower(*args))

    # -- abstract lowering signatures (bake + warm share these) ----

    def _sds(self, shape, dtype, sharding=None):
        if sharding is None:
            return jax.ShapeDtypeStruct(shape, dtype)
        return jax.ShapeDtypeStruct(shape, dtype, sharding=sharding)

    def _mesh_sh(self) -> tuple:
        return ((self._rep_sharding(), self._repl_sharding())
                if self.mesh is not None else (None, None))

    def _eval_structs(self, rows: int, plan) -> tuple:
        """The eval program's full abstract signature at `rows` —
        what `tools/bake.py` lowers against when no reports exist.
        Shapes mirror the runners' concrete args exactly (per-report
        tensors report-sharded under a mesh, small round inputs
        replicated); drift between this and a real call surfaces as
        a cache miss, never a wrong program."""
        from ..backend.incremental import Carry, round_inputs
        from ..backend.vidpf_jax import BatchedCorrectionWords

        (rep, repl) = self._mesh_sh()
        vid = self.bm.vidpf
        (bits, vl) = (vid.BITS, vid.VALUE_LEN)
        n = self.bm.spec.num_limbs
        w = plan.width
        carry = Carry(
            w=self._sds((rows, bits, w, vl, n), jnp.uint32, rep),
            proof=self._sds((rows, bits, w, 32), jnp.uint8, rep),
            seed=self._sds((rows, w, 16), jnp.uint8, rep),
            ctrl=self._sds((rows, w), jnp.bool_, rep))
        rnd = jax.tree_util.tree_map(
            lambda x: self._sds(x.shape, x.dtype, repl),
            round_inputs(plan))
        (erk, crk) = jax.eval_shape(
            lambda nn: self.bm.vidpf.roundkeys(self.ctx, nn),
            jax.ShapeDtypeStruct((rows, 16), jnp.uint8))
        cws = BatchedCorrectionWords(
            seed=self._sds((rows, bits, 16), jnp.uint8, rep),
            ctrl=self._sds((rows, bits, 2), jnp.bool_, rep),
            w=self._sds((rows, bits, vl, n), jnp.uint32, rep),
            proof=self._sds((rows, bits, 32), jnp.uint8, rep))
        vk = self._sds((self.bm.m.VERIFY_KEY_SIZE,), jnp.uint8, repl)
        return (vk, carry, carry, rnd,
                self._sds(erk.shape, erk.dtype, rep),
                self._sds(crk.shape, crk.dtype, rep), cws)

    def _agg_structs(self, rows: int, out_cols: int) -> tuple:
        (rep, _repl) = self._mesh_sh()
        n = self.bm.spec.num_limbs
        s_out = self._sds((rows, out_cols, n), jnp.uint32, rep)
        s_mask = self._sds((rows,), jnp.bool_, rep)
        return (s_out, s_out) + (s_mask,) * 6

    def _batch_structs(self, rows: int):
        from ..backend.mastic_jax import ReportBatch
        from ..backend.vidpf_jax import BatchedCorrectionWords

        (rep, _repl) = self._mesh_sh()
        m = self.bm.m
        vid = self.bm.vidpf
        (bits, vl) = (vid.BITS, vid.VALUE_LEN)
        n = self.bm.spec.num_limbs
        use_jr = m.flp.JOINT_RAND_LEN > 0

        def u8(*shape):
            return self._sds(shape, jnp.uint8, rep)

        return ReportBatch(
            nonces=u8(rows, 16),
            cws=BatchedCorrectionWords(
                seed=u8(rows, bits, 16),
                ctrl=self._sds((rows, bits, 2), jnp.bool_, rep),
                w=self._sds((rows, bits, vl, n), jnp.uint32, rep),
                proof=u8(rows, bits, 32)),
            keys=u8(rows, 2, 16),
            leader_proofs=self._sds((rows, m.flp.PROOF_LEN, n),
                                    jnp.uint32, rep),
            helper_seeds=u8(rows, 32),
            leader_seeds=u8(rows, 32) if use_jr else None,
            peer_parts=tuple(u8(rows, 32) if use_jr else None
                             for _ in range(2)))

    def _wc_structs(self, rows: int) -> tuple:
        (rep, repl) = self._mesh_sh()
        vid = self.bm.vidpf
        n = self.bm.spec.num_limbs
        vk = self._sds((self.bm.m.VERIFY_KEY_SIZE,), jnp.uint8, repl)
        w = self._sds((rows, 2, vid.VALUE_LEN, n), jnp.uint32, rep)
        return (vk, self._batch_structs(rows), w, w)

    def _rk_structs(self, rows: int) -> tuple:
        return (self._sds((rows, 16), jnp.uint8),)

    def _warm_next(self, plan, args, rows: int) -> float:
        """Ahead-of-time compile the predicted next level's (bucket,
        width) programs.  Called at the point where every in-flight
        chunk's device work is already dispatched and the host is
        about to idle in the round's blocking sync, so the XLA work
        overlaps device execution (async dispatch keeps the device
        computing through it).  Lowering signatures are built from
        this round's concrete args with the predicted plan's
        traced-input shapes swapped in — no device memory is touched.
        Returns the seconds spent (the timeline's warm_ms)."""
        from ..backend.incremental import round_inputs
        from . import pipeline as pl

        if not pl.pipeline_enabled():
            return 0.0
        structs = jax.tree_util.tree_map(pl.to_struct, args)
        layouts_next = list(self.layouts) + [plan.layout_new]
        out_len = 1 + self.bm.m.flp.OUTPUT_LEN
        n = self.bm.spec.num_limbs
        eval_jit = self._eval_jit()
        combine_jit = self._combine_jit()
        # Mesh rounds warm with the shardings the real call passes:
        # per-report tensors P("reports"), the small round inputs
        # replicated (mirroring place_reports / place_replicated in
        # the runners' stage phase).
        (rep, repl) = ((self._rep_sharding(), self._repl_sharding())
                       if self.mesh is not None else (None, None))

        def struct(shape, dtype, sharding):
            if sharding is None:
                return jax.ShapeDtypeStruct(shape, dtype)
            return jax.ShapeDtypeStruct(shape, dtype,
                                        sharding=sharding)

        spent = 0.0
        for nplan in pl.predicted_next_plans(
                plan.prefixes, plan.level, self.bm.m.vidpf.BITS,
                self.width, layouts_next):
            nrnd = jax.tree_util.tree_map(
                lambda x: struct(x.shape, x.dtype, repl),
                round_inputs(nplan))
            eargs = structs[:3] + (nrnd,) + structs[4:]
            ekey = self._eval_key(rows, nplan)
            self._warmed_keys.add(ekey)
            spent += self.programs.warm(
                ekey, lambda: eval_jit.lower(*eargs))
            out_cols = len(nplan.out_idx) * out_len
            s_out = struct((rows, out_cols, n), jnp.uint32, rep)
            s_mask = struct((rows,), jnp.bool_, rep)
            cargs = (s_out, s_out) + (s_mask,) * 6
            akey = self._agg_key(rows, out_cols)
            self._warmed_keys.add(akey)
            spent += self.programs.warm(
                akey, lambda: combine_jit.lower(*cargs))
        return spent

    def _aot_summary(self, rows: int, plan,
                     compile_wait_ms: float) -> dict:
        """The round's AOT record for RoundMetrics.extra: whether the
        eval key had been predicted+warmed, what the cache has done so
        far, and the compile wait this round actually paid."""
        key = self._eval_key(rows, plan)
        # Display form drops the runtime/family suffix (constant per
        # process; the full key is what the cache and store use).
        return {
            "eval_key": "x".join(str(k) for k in key[1:-2]),
            "predicted": key in self._warmed_keys,
            "compile_wait_ms": round(compile_wait_ms, 2),
            **self.programs.stats,
        }

    def _wc_fn(self, level: int):
        fn = self._wc_fns.get(level)
        if fn is None:
            (bm, ctx) = (self.bm, self.ctx)
            kwargs: dict = {}
            if self.mesh is not None:
                # Per-report verdict masks stay report-sharded so the
                # combine program's warm-lowered input shardings match.
                kwargs["out_shardings"] = self._rep_sharding()
            fn = jax.jit(lambda vk, b, w0, w1: bm.weight_check_device(
                vk, ctx, level, b, w0, w1), **kwargs)
            self._wc_fns[level] = fn
        return fn

    def _plan(self, prefixes, level):
        from ..backend.incremental import RoundPlan

        while True:
            try:
                return RoundPlan(prefixes, level,
                                 self.bm.m.vidpf.BITS, self.width,
                                 self.layouts)
            except ValueError as err:
                if "exceeds padded width" not in str(err):
                    raise
                self._grow(self.width * 2)


class _IncrementalRunner(RoundPrograms):
    """Drives backend/incremental.py across the collector loop: keeps
    both aggregators' carries, grows the padded width on demand
    (recompiling at most log2(max_width) times), and folds the
    weight-check FLP verdict of the level-0 round in via the fused
    round program."""

    def __init__(self, bm: BatchedMastic, verify_key: bytes, ctx: bytes,
                 batch: ReportBatch, reports: Optional[list] = None,
                 width: int = 8):
        from ..backend.incremental import IncrementalMastic

        self.bm = bm
        self.verify_key = verify_key
        self.ctx = ctx
        self.batch = batch
        self.reports = reports
        self.num_reports = int(batch.nonces.shape[0])
        # Reports whose XOF rejection sampling fired at some round:
        # their device carry holds garbage from that round onward, so
        # they are excluded from every subsequent device aggregate and
        # recomputed through the scalar layer each round instead.
        self.fallback = np.zeros(self.num_reports, bool)
        self.width = max(4, width)
        self.mesh = None  # set via parallel.mesh.shard_incremental_runner
        self.engine = IncrementalMastic(bm, self.width)
        self.layouts: list = []  # per-depth creation layouts
        self._init_programs()
        # Warm artifact store: the first round's programs land in
        # the in-process tier here, so even the key-schedule below
        # and round 0 never trace (drivers/artifacts.py); deeper
        # levels prefetch in the overlapped warm slot.
        self._preload_first_round(self.num_reports, self.num_reports)
        (rk_prog, _rk_wait) = self._rk_program(self.num_reports,
                                               (batch.nonces,))
        (self.ext_rk, self.conv_rk) = rk_prog(batch.nonces)
        self.carries = [
            self.engine.init_carry(self.num_reports,
                                   batch.keys[:, a], a)
            for a in range(2)
        ]

    def memory_accounting(self) -> dict:
        """Device-resident footprint: both carries, the round keys and
        the whole report batch live in HBM for the full run (the
        chunked runner's memory_accounting is the streaming twin —
        this mode only exists while the carry fits one chip)."""
        # .nbytes is metadata — no device->host transfer.
        carry = 2 * sum(x.nbytes for x in self.carries[0])
        rk = self.ext_rk.nbytes + self.conv_rk.nbytes
        batch = sum(x.nbytes
                    for x in jax.tree_util.tree_leaves(self.batch))
        return {
            "chunk_size": 0,
            "num_chunks": 1,
            "device_bytes_total": carry + rk + batch,
            "device_carry_bytes": carry,
            "host_bytes_total": 0,
        }

    def _grow(self, width: int) -> None:
        from ..backend.incremental import Carry, IncrementalMastic

        pad_nodes = width - self.width
        self.carries = [
            Carry(
                w=jnp.pad(c.w, ((0, 0), (0, 0), (0, pad_nodes),
                                (0, 0), (0, 0))),
                proof=jnp.pad(c.proof,
                              ((0, 0), (0, 0), (0, pad_nodes), (0, 0))),
                seed=jnp.pad(c.seed, ((0, 0), (0, pad_nodes), (0, 0))),
                ctrl=jnp.pad(c.ctrl, ((0, 0), (0, pad_nodes))),
            )
            for c in self.carries
        ]
        if self.mesh is not None:
            from ..parallel.mesh import place_reports
            self.carries = [place_reports(self.mesh, c)
                            for c in self.carries]
        self.width = width
        self.engine = IncrementalMastic(self.bm, width)
        # The AOT programs (self.programs) key on the shapes they
        # close over, so the grown width simply maps to fresh keys —
        # only the jitted closures (which capture the engine) need
        # rebinding.
        self._eval_fn = None
        self._combine_fn = None

    def round_stage(self, agg_param) -> dict:
        """The non-blocking half of one resident round: plan, program
        fetch, async dispatch of the whole eval -> weight-check ->
        mask-combine -> aggregate chain, the predicted-next-level
        warm slot, and the carry handover — everything short of the
        blocking sync.  Returns the in-flight handle
        `round_collect` consumes.  The overlapped epoch executor
        (drivers/service.py, ISSUE 10) calls the pair split across
        tenants: another tenant's stage runs here while this handle's
        device work computes."""
        from ..backend.incremental import round_inputs
        from .chunked import check_round_peak

        (level, prefixes, do_weight_check) = agg_param
        plan = self._plan(prefixes, level)
        check_round_peak(
            self.bm,
            len(plan.onehot_idx), len(plan.payload_parent),
            self.num_reports,
            self.memory_accounting()["device_bytes_total"], level,
            (self.mesh.shape["reports"]
             if self.mesh is not None else 1))
        from .pipeline import paused_gc

        t0 = time.perf_counter()
        with paused_gc():
            # GC paused for the dispatch window: its traces segfault
            # this jaxlib if a collection fires mid-trace
            # (pipeline.paused_gc).
            rnd = round_inputs(plan)
            vk_arr = _vk_array(self.verify_key)
            valid = jnp.asarray(~self.fallback)
            ones = jnp.ones(self.num_reports, bool)
            if self.mesh is not None:
                # Deterministic shardings for the AOT programs: small
                # round inputs replicated, per-report masks sharded
                # (mirrors the chunked runner's stage placement).
                from ..parallel.mesh import (place_replicated,
                                             place_reports)
                (rnd, vk_arr) = place_replicated(self.mesh,
                                                 (rnd, vk_arr))
                (valid, ones) = place_reports(self.mesh,
                                              (valid, ones))
            t_up = time.perf_counter()

            args = (vk_arr, self.carries[0], self.carries[1], rnd,
                    self.ext_rk, self.conv_rk, self.batch.cws)
            inline_before = self.programs.stats["inline_compiles"]
            (eval_prog, compile_s) = self._eval_program(
                self.num_reports, plan, args)
            t_disp0 = time.perf_counter()
            (c0, c1, out0, out1, accept_ev, ok) = eval_prog(*args)
            wc_checks = {}
            wc_compile_s = 0.0
            (wc_accept, wc_okdev, jr) = (ones, ones, ones)
            if do_weight_check:
                # FLP weight check on the depth-0 payload rows the
                # tree program just computed (rows 0..1 of depth 0 are
                # always the two root children) — a small FLP-only
                # program, not a second from-root tree eval.
                wcargs = (vk_arr, self.batch, c0.w[:, 0, :2],
                          c1.w[:, 0, :2])
                (wc_prog, wc_compile_s) = self._wc_program(
                    self.num_reports, level, wcargs)
                (wc_checks, wc_okdev) = wc_prog(*wcargs)
                wc_accept = wc_checks["weight_check"]
                jr = wc_checks.get("joint_rand", ones)
            cargs = (out0, out1, accept_ev, ok, valid,
                     wc_accept, wc_okdev, jr)
            (agg_prog, agg_compile_s) = self._agg_program(
                self.num_reports, cargs)
            (accept_dev, agg0, agg1) = agg_prog(*cargs)
            t_disp1 = time.perf_counter()
            # Everything is dispatched; the device computes while the
            # host compiles the predicted next level's programs.
            warm_s = self._warm_next(plan, args, self.num_reports)
        t_warm = time.perf_counter()
        self.carries = [c0, c1]
        assert level == len(self.layouts)
        self.layouts.append(plan.layout_new)
        return {
            "agg_param": agg_param, "plan": plan,
            "accept_dev": accept_dev, "agg0": agg0, "agg1": agg1,
            "ok": ok, "wc_okdev": wc_okdev, "accept_ev": accept_ev,
            "wc_checks": wc_checks,
            "compile_s": (compile_s, wc_compile_s, agg_compile_s),
            # Whether any of this round's program fetches actually
            # paid an inline XLA compile — an artifact-store load's
            # wait is attributed in extra["artifacts"].load_ms, and
            # the timeline compile field stays an inline-only claim.
            "compiled_inline": (self.programs.stats["inline_compiles"]
                                > inline_before),
            "warm_s": warm_s,
            "t": (t0, t_up, t_disp0, t_disp1, t_warm),
        }

    def round_collect(self, handle: dict,
                      metrics_out: Optional[list] = None) -> list:
        """The blocking half: the round's SINGLE sync, downloads, the
        scalar-fallback splice, metrics.  Everything in the handle is
        an in-flight future until here."""
        (level, prefixes, do_weight_check) = handle["agg_param"]
        agg_param = handle["agg_param"]
        plan = handle["plan"]
        (accept_dev, agg0, agg1) = (handle["accept_dev"],
                                    handle["agg0"], handle["agg1"])
        (ok, wc_okdev) = (handle["ok"], handle["wc_okdev"])
        (accept_ev, wc_checks) = (handle["accept_ev"],
                                  handle["wc_checks"])
        (compile_s, wc_compile_s, agg_compile_s) = handle["compile_s"]
        warm_s = handle["warm_s"]
        (t0, t_up, t_disp0, t_disp1, t_warm) = handle["t"]

        shard_skew = None
        if self.mesh is not None \
                and self.mesh.shape["reports"] > 1:
            # Per-shard completion skew inside the one sync window
            # (same probe as the chunked collect); observability only.
            t_sk = time.perf_counter()
            waits = []
            for sh in accept_dev.addressable_shards:
                sh.data.block_until_ready()
                waits.append((time.perf_counter() - t_sk) * 1e3)
            shard_skew = round(max(waits) - min(waits), 3)
        jax.block_until_ready(
            (accept_dev, agg0, agg1, ok, wc_okdev))
        t_wait = time.perf_counter()
        checks = {"eval_proof": np.asarray(accept_ev)}
        checks.update({k: np.asarray(v)
                       for (k, v) in wc_checks.items()})
        self.fallback |= ~np.asarray(ok)
        if do_weight_check:
            self.fallback |= ~np.asarray(wc_okdev)
        accept = np.asarray(accept_dev).copy()
        rows = len(prefixes) * (1 + self.bm.m.flp.OUTPUT_LEN)
        agg_shares = [
            self.bm.agg_share_to_host(np.asarray(a)[:rows])
            for a in (agg0, agg1)
        ]
        t_down = time.perf_counter()

        metrics = RoundMetrics(level=level,
                               frontier_width=len(prefixes),
                               padded_width=self.width,
                               reports_total=self.num_reports)
        attribute_rejections(metrics, checks["eval_proof"],
                             checks.get("weight_check"),
                             checks.get("joint_rand"),
                             device_ok=~self.fallback)
        # The incremental round extends only the surviving parents.
        count_round_ops(metrics, self.bm.m, self.num_reports,
                        2 * plan.parent_count,
                        include_key_setup=(level == 0))
        count_round_bytes(metrics, self.bm.m, agg_param,
                          self.num_reports)

        splice_rejected(self.bm.m, self.verify_key, self.ctx, agg_param,
                        self.reports, ~self.fallback, accept, agg_shares)
        metrics.accepted = int(accept.sum())
        metrics.xof_fallbacks = int(self.fallback.sum())
        metrics.rejected_fallback = int((self.fallback & ~accept).sum())
        t_host = time.perf_counter()
        # Inline-compile waits only: when every program came from the
        # cache/store tiers, the (small) fetch waits stay out of the
        # compile field — `artifacts.load_ms` attributes them.
        compile_ms = ((compile_s + agg_compile_s + wc_compile_s) * 1e3
                      if handle["compiled_inline"] else 0.0)
        metrics.extra["artifacts"] = self._artifacts_block()
        if self.mesh is not None:
            metrics.extra["mesh"] = {
                "report_shards": self.mesh.shape["reports"],
                "device_rows_per_chunk": self.num_reports,
                "rows_per_shard": (self.num_reports
                                   // self.mesh.shape["reports"]),
                "psum_bytes_per_round": agg0.nbytes + agg1.nbytes,
                "shard_wait_skew_ms_p50": shard_skew or 0.0,
                "shard_wait_skew_ms_max": shard_skew or 0.0,
            }
        metrics.extra["pipeline"] = {
            "mode": "resident-deferred",
            "fallback": None,
            "round_wall_ms": round((t_host - t0) * 1e3, 2),
            "overlap_efficiency": 0.0,  # one chunk: nothing to overlap
            "compile_inline_ms": round(compile_ms, 2),
            "phases": {
                "upload_ms": round((t_up - t0) * 1e3, 3),
                "compile_ms": round(compile_ms, 3),
                "dispatch_ms": round(
                    (t_disp1 - t_disp0 - agg_compile_s
                     - wc_compile_s) * 1e3, 3),
                "warm_ms": round(warm_s * 1e3, 3),
                "compute_wait_ms": round((t_wait - t_warm) * 1e3, 3),
                "download_ms": round((t_down - t_wait) * 1e3, 3),
                "host_ms": round((t_host - t_down) * 1e3, 3),
            },
            "host_syncs": 1,
            "aot": self._aot_summary(self.num_reports, plan,
                                     compile_ms),
        }
        if metrics_out is not None:
            metrics_out.append(metrics)
        num = int(accept.sum())
        return self.bm.m.unshard(agg_param, agg_shares, num)

    def round(self, agg_param,
              metrics_out: Optional[list] = None) -> list:
        """One resident round, pipelined-executor style: the whole
        eval -> weight-check -> mask-combine -> aggregate chain is
        dispatched asynchronously (device-side accept combine instead
        of host boolean folds), the predicted next level's programs
        warm in the background, and ONE blocking sync collects
        everything — the per-phase timeline lands in
        `RoundMetrics.extra["pipeline"]`.  `round_stage` /
        `round_collect` are the same round split at the sync seam
        (the overlapped epoch executor's unit of interleaving)."""
        return self.round_collect(self.round_stage(agg_param),
                                  metrics_out=metrics_out)
