"""Inter-party wire decoders + the process-separated leader/helper.

The decoders must invert the conformance-locked encoders for every
instantiation; the subprocess demo must reproduce a conformance
vector's aggregate shares byte for byte with leader and helper as
separate OS processes exchanging only wire bytes (VERDICT r2 item 6;
reference wire types /root/reference/poc/mastic.py:31-49).
"""

import json
import os

import pytest

pytestmark = pytest.mark.slow


from mastic_tpu import wire
from mastic_tpu.common import gen_rand
from mastic_tpu.mastic import (MasticCount, MasticHistogram, MasticSum,
                               MasticSumVec)
from mastic_tpu.testvec_codec import (encode_agg_share,
                                      encode_input_share,
                                      encode_prep_share)

TEST_VEC_DIR = os.environ.get(
    "MASTIC_TEST_VEC", "/root/reference/test_vec/mastic")

INSTANCES = [
    (MasticCount(2), (True, False), 1),
    (MasticSum(2, 7), (False, True), 5),
    (MasticSumVec(4, 3, 1, 1), (True, False, True, True), [1, 0, 1]),
    (MasticHistogram(2, 4, 2), (False, False), 3),
]


@pytest.mark.parametrize("case", INSTANCES,
                         ids=[type(m).__name__ for (m, _, _) in INSTANCES])
def test_wire_roundtrip(case) -> None:
    (m, alpha, weight) = case
    ctx = b"wire test"
    nonce = gen_rand(m.NONCE_SIZE)
    rand = gen_rand(m.RAND_SIZE)
    (public_share, input_shares) = m.shard(ctx, (alpha, weight), nonce,
                                           rand)
    for agg_id in range(2):
        blob = encode_input_share(m, input_shares[agg_id])
        assert len(blob) == wire.input_share_size(m, agg_id)
        assert wire.decode_input_share(m, agg_id, blob) == \
            input_shares[agg_id]
        report = wire.encode_report(m, agg_id, nonce, public_share,
                                    input_shares[agg_id])
        (rn, rps, rshare) = wire.decode_report(m, agg_id, report)
        assert rn == nonce and rps == public_share \
            and rshare == input_shares[agg_id]

    level = len(alpha) - 1
    agg_param = (level, (alpha,), True)
    verify_key = gen_rand(m.VERIFY_KEY_SIZE)
    states = []
    shares = []
    for agg_id in range(2):
        (state, share) = m.prep_init(verify_key, ctx, agg_id, agg_param,
                                     nonce, public_share,
                                     input_shares[agg_id])
        states.append(state)
        shares.append(share)
        blob = encode_prep_share(m, share)
        assert len(blob) == wire.prep_share_size(m, agg_param)
        assert wire.decode_prep_share(m, agg_param, blob) == share
    prep_msg = m.prep_shares_to_prep(ctx, agg_param, shares)
    assert wire.decode_prep_msg(m, agg_param, prep_msg or b"") == \
        prep_msg
    out = m.prep_next(ctx, states[0], prep_msg)
    agg = m.agg_update(agg_param, m.agg_init(agg_param), out)
    blob = encode_agg_share(m, agg)
    assert len(blob) == wire.agg_share_size(m, agg_param)
    assert wire.decode_agg_share(m, agg_param, blob) == agg


def _load_vector(name: str) -> dict:
    with open(os.path.join(TEST_VEC_DIR, name)) as f:
        return json.load(f)


def _subprocess_round(mastic, spec, vec):
    from mastic_tpu.drivers.parties import ProcessCollector

    ctx = bytes.fromhex(vec["ctx"])
    verify_key = bytes.fromhex(vec["verify_key"])
    reports = []
    for prep in vec["prep"]:
        nonce = bytes.fromhex(prep["nonce"])
        public_share = mastic.vidpf.decode_public_share(
            bytes.fromhex(prep["public_share"]))
        input_shares = [
            wire.decode_input_share(mastic, agg_id,
                                    bytes.fromhex(raw))
            for (agg_id, raw) in enumerate(prep["input_shares"])
        ]
        reports.append((nonce, public_share, input_shares))
    agg_param = mastic.decode_agg_param(bytes.fromhex(vec["agg_param"]))

    coll = ProcessCollector(mastic, spec, ctx, verify_key)
    try:
        coll.upload(reports)
        (result, accept, share_bytes) = coll.round(agg_param)
    finally:
        coll.close()
    return (result, accept, share_bytes)


@pytest.mark.parametrize("name,spec", [
    ("MasticCount_0.json", {"class": "MasticCount", "args": [2]}),
    ("MasticHistogram_0.json",
     {"class": "MasticHistogram", "args": [2, 4, 2]}),
])
def test_process_separated_conformance(name, spec) -> None:
    """Two OS processes reproduce the vector's aggregate shares byte
    for byte (incl. a joint-rand instantiation)."""
    vec = _load_vector(name)
    from mastic_tpu.drivers.parties import instantiate

    mastic = instantiate(spec)
    assert vec["vidpf_bits"] == mastic.vidpf.BITS
    (result, accept, share_bytes) = _subprocess_round(mastic, spec, vec)
    assert accept.all()
    assert [share_bytes[0].hex(), share_bytes[1].hex()] == \
        vec["agg_shares"]
    assert result == vec["agg_result"]


def test_resolve_rejects_malformed_peer_blob() -> None:
    """A truncated or oversized prep-share exchange is refused as a
    protocol error, not a numpy reshape traceback (ADVICE r4)."""
    from mastic_tpu.drivers.parties import AggregatorParty

    m = MasticCount(2)
    ctx = b"wire test"
    verify_key = gen_rand(m.VERIFY_KEY_SIZE)
    blobs = []
    for alpha in ((True, False), (False, True)):
        nonce = gen_rand(m.NONCE_SIZE)
        rand = gen_rand(m.RAND_SIZE)
        (ps, shares) = m.shard(ctx, (alpha, 1), nonce, rand)
        blobs.append([wire.encode_report(m, a, nonce, ps, shares[a])
                      for a in range(2)])
    parties = [AggregatorParty(m, a, verify_key, ctx)
               for a in range(2)]
    for a in range(2):
        parties[a].load_reports([b[a] for b in blobs])
    agg_param = (0, ((False,), (True,)), True)
    _leader_blob = parties[0].prep_blob(agg_param)
    helper_blob = parties[1].prep_blob(agg_param)

    with pytest.raises(ValueError, match="malformed prep-share"):
        parties[0].resolve(agg_param, helper_blob[:-1])
    with pytest.raises(ValueError, match="malformed prep-share"):
        parties[0].resolve(agg_param, helper_blob + b"\x00")
    (accept, resolution) = parties[0].resolve(agg_param, helper_blob)
    assert accept.all()

    # Symmetric guard on the helper side: a truncating leader is a
    # protocol error, whether the bitmap or a prep-msg frame is cut.
    with pytest.raises(ValueError, match="malformed resolution"):
        parties[1].confirm(agg_param, b"")
    with pytest.raises(ValueError, match="truncated"):
        parties[1].confirm(agg_param, resolution[:-1])
    assert parties[1].confirm(agg_param, resolution).all()


def test_process_separated_rejects_tampered_report() -> None:
    """A tampered VIDPF key is rejected by the process-separated
    round (the accept bitmap excludes it) without disturbing honest
    reports."""
    spec = {"class": "MasticCount", "args": [2]}
    vec = _load_vector("MasticCount_0.json")
    from mastic_tpu.drivers.parties import instantiate

    mastic = instantiate(spec)
    ctx = bytes.fromhex(vec["ctx"])
    verify_key = bytes.fromhex(vec["verify_key"])
    reports = []
    for (i, prep) in enumerate(vec["prep"]):
        nonce = bytes.fromhex(prep["nonce"])
        public_share = mastic.vidpf.decode_public_share(
            bytes.fromhex(prep["public_share"]))
        input_shares = [
            wire.decode_input_share(mastic, agg_id,
                                    bytes.fromhex(raw))
            for (agg_id, raw) in enumerate(prep["input_shares"])
        ]
        if i == 0:  # flip a key bit of the leader's share
            (key, proof, seed, part) = input_shares[0]
            key = bytes([key[0] ^ 1]) + key[1:]
            input_shares[0] = (key, proof, seed, part)
        reports.append((nonce, public_share, input_shares))
    agg_param = mastic.decode_agg_param(bytes.fromhex(vec["agg_param"]))

    from mastic_tpu.drivers.parties import ProcessCollector

    coll = ProcessCollector(mastic, spec, ctx, verify_key)
    try:
        coll.upload(reports)
        (result, accept, _shares) = coll.round(agg_param)
    finally:
        coll.close()
    assert not accept[0] and accept[1:].all()

    # The honest remainder must equal the oracle over those reports.
    measurements = [vec["prep"][i]["measurement"]
                    for i in range(1, len(vec["prep"]))]
    (level, prefixes, _wc) = agg_param
    expected = []
    for prefix in prefixes:
        total = 0
        for raw in measurements:
            (alpha_raw, weight) = raw
            alpha = tuple(bool(b) for b in alpha_raw)
            if alpha[:level + 1] == tuple(prefix):
                total += weight
        expected.append(total)
    assert result == expected
