"""AES-128 block cipher (encrypt-only, ECB).

Backbone of `XofFixedKeyAes128` (draft-irtf-cfrg-vdaf-13 §6.2.2), the
XOF driving every VIDPF tree extend/convert step
(/root/reference/poc/vidpf.py:330-364).  The S-box and round constants
are generated from first principles (GF(2^8) inversion + affine map)
rather than embedded as opaque tables, and the implementation is
self-tested against the FIPS-197 known-answer vector.

This is the scalar CPU reference; the batched bitsliced TPU kernel
lives in mastic_tpu/ops/aes_jax.py and must match it byte-for-byte.
"""


def _gf_mul(a: int, b: int) -> int:
    """Multiply in GF(2^8) modulo x^8 + x^4 + x^3 + x + 1 (0x11B)."""
    out = 0
    while b:
        if b & 1:
            out ^= a
        a <<= 1
        if a & 0x100:
            a ^= 0x11B
        b >>= 1
    return out


def _gen_sbox() -> bytes:
    # Multiplicative inverse table via exp/log over generator 3.
    exp = [0] * 256
    log = [0] * 256
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x = _gf_mul(x, 3)
    exp[255] = exp[0]

    sbox = bytearray(256)
    for value in range(256):
        inv = 0 if value == 0 else exp[255 - log[value]]
        # Affine transform: b'_i = b_i ^ b_{i+4} ^ b_{i+5} ^ b_{i+6}
        #                        ^ b_{i+7} ^ c_i  with c = 0x63.
        res = 0
        for i in range(8):
            bit = ((inv >> i) ^ (inv >> ((i + 4) % 8))
                   ^ (inv >> ((i + 5) % 8)) ^ (inv >> ((i + 6) % 8))
                   ^ (inv >> ((i + 7) % 8)) ^ (0x63 >> i)) & 1
            res |= bit << i
        sbox[value] = res
    return bytes(sbox)


SBOX: bytes = _gen_sbox()
assert SBOX[0x00] == 0x63 and SBOX[0x01] == 0x7C and SBOX[0x53] == 0xED


def _expand_key(key: bytes) -> list[bytes]:
    """AES-128 key schedule: 11 round keys of 16 bytes."""
    assert len(key) == 16
    words = [key[4 * i:4 * i + 4] for i in range(4)]
    rcon = 1
    for i in range(4, 44):
        temp = words[i - 1]
        if i % 4 == 0:
            # mastic-allow: SF002 — scalar CPU reference only: the
            # TPU path computes SubBytes as a bitsliced boolean
            # circuit with no table lookups (ops/aes_jax.py,
            # ops/sbox_tower.py), which is the constant-time form
            temp = bytes([SBOX[temp[1]] ^ rcon, SBOX[temp[2]],
                          SBOX[temp[3]], SBOX[temp[0]]])
            rcon = _gf_mul(rcon, 2)
        words.append(bytes(a ^ b for (a, b) in zip(words[i - 4], temp)))
    return [b"".join(words[4 * r:4 * r + 4]) for r in range(11)]


def _mix_single_column(col: bytes) -> bytes:
    (a0, a1, a2, a3) = col
    return bytes([
        _gf_mul(a0, 2) ^ _gf_mul(a1, 3) ^ a2 ^ a3,
        a0 ^ _gf_mul(a1, 2) ^ _gf_mul(a2, 3) ^ a3,
        a0 ^ a1 ^ _gf_mul(a2, 2) ^ _gf_mul(a3, 3),
        _gf_mul(a0, 3) ^ a1 ^ a2 ^ _gf_mul(a3, 2),
    ])


class Aes128:
    """AES-128 with a precomputed key schedule; `encrypt_block` maps one
    16-byte block (column-major state order per FIPS-197)."""

    def __init__(self, key: bytes):
        self.round_keys = _expand_key(key)

    def encrypt_block(self, block: bytes) -> bytes:
        assert len(block) == 16
        state = bytes(a ^ b for (a, b) in zip(block, self.round_keys[0]))
        for round_index in range(1, 11):
            # SubBytes
            # mastic-allow: SF002 — scalar CPU reference only; the
            # constant-time path is the bitsliced circuit in ops/
            state = bytes(SBOX[b] for b in state)
            # ShiftRows: row r (bytes r, r+4, r+8, r+12) rotates left by r.
            state = bytes(state[(i + 4 * (i % 4)) % 16] for i in range(16))
            # MixColumns (skipped in the final round)
            if round_index < 10:
                state = b"".join(_mix_single_column(state[4 * c:4 * c + 4])
                                 for c in range(4))
            state = bytes(a ^ b
                          for (a, b) in zip(state, self.round_keys[round_index]))
        return state
