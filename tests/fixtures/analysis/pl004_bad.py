"""Known-bad: sublane block dim neither 1 nor 8-aligned (PL004)."""

from jax.experimental import pallas as pl

_ROWS = 12


def spec():
    return pl.BlockSpec((_ROWS, 128), lambda i: (0, i))
