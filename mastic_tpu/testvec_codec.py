"""Test-vector JSON codec: hex encodings of the wire messages in the
schema of /root/reference/test_vec/mastic/*.json.

These encoders live outside the protocol class on purpose — they are
a test-harness concern (the upstream analog is the vdaf_poc test_utils
machinery, not the VDAF itself), and only the conformance suite and
the vector generator consume them.
"""

from typing import Any

from .mastic import (Mastic, MasticInputShare, MasticPrepMessage,
                     MasticPrepShare)
from .vidpf import CorrectionWord


def set_type_param(mastic: Mastic, test_vec: dict[str, Any]) -> list[str]:
    test_vec["vidpf_bits"] = int(mastic.vidpf.BITS)
    return ["vidpf_bits"] + \
        mastic.flp.valid.test_vec_set_type_param(test_vec)


def encode_input_share(mastic: Mastic,
                       input_share: MasticInputShare) -> bytes:
    (key, proof_share, seed, peer_joint_rand_part) = input_share
    optional = [
        mastic.field.encode_vec(proof_share)
        if proof_share is not None else b"",
        seed or b"",
        peer_joint_rand_part or b"",
    ]
    return key + b"".join(optional)


def encode_public_share(mastic: Mastic,
                        correction_words: list[CorrectionWord]) -> bytes:
    return mastic.vidpf.encode_public_share(correction_words)


def encode_agg_share(mastic: Mastic, agg_share: list) -> bytes:
    return mastic.field.encode_vec(agg_share) if agg_share else b""


def encode_prep_share(mastic: Mastic,
                      prep_share: MasticPrepShare) -> bytes:
    (eval_proof, verifier_share, joint_rand_part) = prep_share
    return eval_proof + (joint_rand_part or b"") + (
        mastic.field.encode_vec(verifier_share)
        if verifier_share is not None else b"")


def encode_prep_msg(mastic: Mastic,
                    prep_message: MasticPrepMessage) -> bytes:
    return prep_message or b""
