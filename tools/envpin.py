"""Pre-import environment pinning for the serving entrypoints.

`tools/serve.py` (and friends) translate argv flags into process
environment *before* importing jax or spawning any thread — the
ingest-front / status-server threads that exist later only ever READ
the environment (e.g. the tracer's `MASTIC_TRACE_FILE` probe).  The
writes live in this helper, outside the concurrency analyzer's
service-plane scope, precisely because they are argv-time,
single-threaded setup with a real happens-before edge (thread start)
between them and every reader; keeping them in serve.py would force
a lock (or an allow) around writes no thread can ever race.

Anything that mutates os.environ AFTER threads exist must NOT use
this module — set the lever before boot instead.
"""

import os


def pin(name: str, value: str) -> None:
    """argv-time `os.environ[name] = value` (see module docstring)."""
    os.environ[name] = value


def force_host_devices(n: int) -> None:
    """Pin XLA's virtual host device count (must run before the jax
    import snapshots XLA_FLAGS); a pre-existing setting wins."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count="
            f"{n}").strip()
