"""CC002 bad fixture: the two lock orders invert (ABBA)."""
import threading

_lock_a = threading.Lock()
_lock_b = threading.Lock()


def forward():
    with _lock_a:
        with _lock_b:
            pass


def backward():
    with _lock_b:
        with _lock_a:
            pass
