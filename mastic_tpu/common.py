"""Byte-string and vector helpers.

Replaces the `vdaf_poc.common` helpers consumed by the reference
implementation (see /root/reference/poc/vidpf.py:7, mastic.py:6-7).
Semantics follow draft-irtf-cfrg-vdaf-13 and are locked against the
conformance vectors in /root/reference/test_vec/mastic/.
"""

from typing import TypeVar

T = TypeVar("T")


def byte(x: int) -> bytes:
    """A single byte."""
    return int(x).to_bytes(1, "big")


def zeros(n: int) -> bytes:
    return bytes(n)


def concat(parts: list[bytes]) -> bytes:
    return b"".join(parts)


def front(length: int, vec: list[T] | bytes) -> tuple:
    """Split `vec` into its first `length` items and the remainder."""
    return (vec[:length], vec[length:])


def xor(left: bytes, right: bytes) -> bytes:
    """XOR of two byte strings (length of the shorter input)."""
    return bytes(a ^ b for (a, b) in zip(left, right))


def to_le_bytes(val: int, length: int) -> bytes:
    return int(val).to_bytes(length, "little")


def from_le_bytes(encoded: bytes) -> int:
    return int.from_bytes(encoded, "little")


def to_be_bytes(val: int, length: int) -> bytes:
    return int(val).to_bytes(length, "big")


def from_be_bytes(encoded: bytes) -> int:
    return int.from_bytes(encoded, "big")


def next_power_of_2(n: int) -> int:
    """Smallest power of 2 that is >= n (n >= 1)."""
    assert n >= 1
    return 1 << (n - 1).bit_length()


def gen_rand(length: int) -> bytes:
    import os

    return os.urandom(length)


def vec_add(left: list, right: list) -> list:
    assert len(left) == len(right)
    return [x + y for (x, y) in zip(left, right)]


def vec_sub(left: list, right: list) -> list:
    assert len(left) == len(right)
    return [x - y for (x, y) in zip(left, right)]


def vec_neg(vec: list) -> list:
    return [-x for x in vec]


def pack_bits(bits: list[bool]) -> bytes:
    """Pack bits into bytes, MSB-first within each byte — the order used
    for prefix-tree paths and agg-param prefixes (reference
    PrefixTreeIndex.encode, vidpf.py:32-39).  NOT the order of the
    public-share control bits; those use `pack_bits_le`.
    """
    out = bytearray((len(bits) + 7) // 8)
    for (i, bit) in enumerate(bits):
        out[i // 8] |= bit << (7 - (i % 8))
    return bytes(out)


def pack_bits_le(bits: list[bool]) -> bytes:
    """Pack bits into bytes, LSB-first within each byte — the order used
    by the VIDPF public-share control bits (vdaf-13 `pack_bits`)."""
    out = bytearray((len(bits) + 7) // 8)
    for (i, bit) in enumerate(bits):
        out[i // 8] |= bit << (i % 8)
    return bytes(out)


def unpack_bits_le(encoded: bytes, num_bits: int) -> list[bool]:
    if len(encoded) != (num_bits + 7) // 8:
        raise ValueError("incorrect length of encoded bits")
    bits = [(encoded[i // 8] >> (i % 8)) & 1 != 0 for i in range(num_bits)]
    leftover = len(encoded) * 8 - num_bits
    if leftover and encoded[-1] >> (8 - leftover):
        raise ValueError("nonzero padding bits")
    return bits


def unpack_bits(encoded: bytes, num_bits: int) -> list[bool]:
    if len(encoded) != (num_bits + 7) // 8:
        raise ValueError("incorrect length of encoded bits")
    bits = [
        (encoded[i // 8] >> (7 - (i % 8))) & 1 != 0
        for i in range(num_bits)
    ]
    # Trailing bits in the final byte must be zero.
    leftover = len(encoded) * 8 - num_bits
    if leftover and encoded[-1] & ((1 << leftover) - 1):
        raise ValueError("nonzero padding bits")
    return bits
