"""Multi-chip proof run: a REAL pipelined chunked heavy-hitters
collection on an n-device mesh, asserted bit-identical to the
single-device serial run.

This graduates `__graft_entry__.dryrun_multichip` (one jitted round on
tiny shapes) to the production execution model end to end: chunked
store -> pipelined double-buffered executor -> mesh-sharded chunk
uploads -> device-side accept combine -> psum-only aggregation, with
the uneven tail chunk padded to the shard multiple and masked.  On a
CPU host the mesh is forced via `--xla_force_host_platform_device_count`
(set before the jax import below); on a real multi-chip attachment the
same code runs over the physical devices.

Prints one JSON line and exits nonzero unless ALL of:
  * mesh-run aggregates, accept masks, rejection counters, fallback
    (quarantine-union) masks and checkpoint state arrays equal the
    serial run's bit for bit;
  * every multi-chunk round ran mode="pipelined" with fallback=None
    (the r9 `("serial", "mesh")` degrade is gone);
  * steady-state rounds after the first paid ZERO inline compile
    (the AOT predictor works sharded).
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--devices", type=int, default=8,
                        help="report-axis mesh size (virtual CPU "
                             "devices are forced when the platform "
                             "is cpu)")
    parser.add_argument("--bits", type=int, default=3)
    parser.add_argument("--chunk-size", type=int, default=4,
                        help="deliberately NOT a multiple of "
                             "--devices by default: exercises the "
                             "pad-to-shard-multiple path")
    parser.add_argument("--out", type=str, default=None)
    args = parser.parse_args()

    # Pin the virtual device count before jax imports (config
    # snapshot); harmless on a real multi-chip attachment.
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count="
            f"{args.devices}").strip()

    import numpy as np
    import jax

    requested = os.environ.get("JAX_PLATFORMS", "").strip()
    if requested and "axon" not in requested.split(","):
        jax.config.update("jax_platforms", requested)

    from mastic_tpu import MasticCount
    from mastic_tpu.common import gen_rand
    from mastic_tpu.drivers.heavy_hitters import (
        HeavyHittersRun, get_reports_from_measurements)
    from mastic_tpu.parallel import make_mesh

    if jax.device_count() < args.devices:
        print(json.dumps({"ok": False,
                          "error": f"need {args.devices} devices, "
                                   f"have {jax.device_count()}"}))
        sys.exit(2)

    m = MasticCount(args.bits)
    ctx = b"multichip"
    # Steady one-child-per-parent frontier (the AOT predictor's fixed
    # point) with one tampered report, so both the zero-inline-compile
    # claim and the rejection attribution are exercised; 10 reports /
    # chunk 4 = 3 chunks with a padded tail.
    meas = [(m.vidpf.test_index_from_int(v, args.bits), True)
            for v in (0, 0, 0, 7, 7, 7, 3, 1, 6, 6)]
    reports = get_reports_from_measurements(m, ctx, meas)
    (nonce, ps, shares) = reports[6]
    (key, proof, seed, part) = shares[0]
    reports[6] = (nonce, ps, [
        (bytes([key[0] ^ 1]) + key[1:], proof, seed, part), shares[1]])
    vk = gen_rand(m.VERIFY_KEY_SIZE)
    thresholds = {"default": 2}

    def collect(mesh):
        run = HeavyHittersRun(m, ctx, thresholds, reports,
                              verify_key=vk,
                              chunk_size=args.chunk_size, mesh=mesh)
        t0 = time.time()
        while run.step():
            pass
        return (run, time.time() - t0)

    (serial, serial_s) = collect(None)
    mesh = make_mesh(args.devices, nodes_axis=1)
    (meshed, meshed_s) = collect(mesh)

    failures = []

    def check(name, cond):
        if not cond:
            failures.append(name)

    check("result", serial.result() == meshed.result())
    check("levels", len(serial.metrics) == len(meshed.metrics))
    for (a, b) in zip(serial.metrics, meshed.metrics):
        check(f"counters_l{a.level}",
              (a.accepted, a.rejected_eval_proof,
               a.rejected_weight_check, a.rejected_joint_rand,
               a.rejected_fallback, a.xof_fallbacks) ==
              (b.accepted, b.rejected_eval_proof,
               b.rejected_weight_check, b.rejected_joint_rand,
               b.rejected_fallback, b.xof_fallbacks))
    check("quarantine_union_mask",
          np.array_equal(serial.runner.fallback,
                         meshed.runner.fallback))
    (sa, sb) = (serial.runner.state_arrays(),
                meshed.runner.state_arrays())
    check("state_keys", sorted(sa) == sorted(sb))
    for k in sa:
        check(f"state_{k}", np.array_equal(sa[k], sb[k]))

    pipes = [mx.extra["pipeline"] for mx in meshed.metrics]
    check("pipelined", all(p["mode"] == "pipelined" for p in pipes))
    check("no_fallback", all(p["fallback"] is None for p in pipes))
    check("zero_inline_after_first",
          all(p["compile_inline_ms"] == 0.0 for p in pipes[1:]))
    check("aot_predicted",
          all(p["aot"]["predicted"] for p in pipes[1:]))

    mesh_rounds = [mx.extra["mesh"] for mx in meshed.metrics]
    out = {
        "n_devices": args.devices,
        "platform": jax.devices()[0].platform,
        "bits": args.bits,
        "reports": len(reports),
        "chunk_size": args.chunk_size,
        "levels": len(meshed.metrics),
        "serial_seconds": round(serial_s, 1),
        "mesh_seconds": round(meshed_s, 1),
        "device_rows_per_chunk":
            mesh_rounds[0]["device_rows_per_chunk"],
        "rows_per_shard": mesh_rounds[0]["rows_per_shard"],
        "psum_bytes_total": sum(mr["psum_bytes_per_round"]
                                for mr in mesh_rounds),
        "pipeline_modes": sorted({p["mode"] for p in pipes}),
        "compile_inline_ms_after_first": round(
            sum(p["compile_inline_ms"] for p in pipes[1:]), 2),
        "hitters": len(meshed.result()),
        "failures": failures,
        "ok": not failures,
    }
    line = json.dumps(out)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    if failures:
        sys.exit(1)


if __name__ == "__main__":
    main()
