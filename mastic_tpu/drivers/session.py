"""Session layer for the process-separated parties: deadlines,
structured errors, and bounded retry.

Everything the transport can do to an aggregation session — a peer
that hangs, dies, truncates, or floods — must surface as a
`SessionError` naming the party and the protocol step, in bounded
time.  This module is that contract:

* `SessionError` — the one exception type the session layer raises for
  transport/protocol faults, carrying (party, step, kind) so the
  collector can attribute and the supervisor can decide retryability;
* `Deadline` — a monotonic budget threaded through every blocking call
  of a round, so N sequential exchanges share one bound instead of
  multiplying per-call timeouts;
* `SessionConfig` — the timeout/retry lever set (env levers
  documented in USAGE.md "Fault model & injection");
* `Channel` — a framed socket channel (same 4-byte LE length framing
  as `wire.frame`) whose every send/recv takes a deadline; the only
  place in the drivers that touches a raw socket read (the RB001
  analyzer rule keeps it that way);
* `with_retries` — bounded exponential backoff for the idempotent
  exchanges (upload, agg-param dispatch, agg-share fetch — prep shares
  are recomputable from the marshaled report arrays, so a round
  restart is always safe).

The fault-injection harness (`drivers/faults.py`) plugs in at the
Channel seam: an injector mutates outbound frames and fires at
protocol checkpoints, which is how the fault-matrix suite
(tests/test_faults.py) drives every failure class through this layer.
"""

import os
import socket
import struct
import time
from dataclasses import dataclass
from typing import Callable, Optional

from ..obs import trace as obs_trace
from ..obs.registry import get_registry

# Error kinds a SessionError carries.  `timeout` and `closed` are the
# retryable transport kinds (the peer may come back after a respawn);
# `malformed`, `crashed` and `protocol` are terminal for the attempt
# but still retryable at the session level after a respawn.  `tls` is
# terminal outright: a wrong-CA, expired or misnamed credential does
# not heal on redial, so retrying would only hammer the listener
# (the refusal reason code rides in the detail, `tls-*`).
KIND_TIMEOUT = "timeout"
KIND_CLOSED = "closed"
KIND_MALFORMED = "malformed"
KIND_CRASHED = "crashed"
KIND_PROTOCOL = "protocol"
KIND_TLS = "tls"

RETRYABLE_KINDS = (KIND_TIMEOUT, KIND_CLOSED, KIND_CRASHED)


class SessionError(RuntimeError):
    """A transport or protocol fault, attributed to a party and a
    protocol step.  Replaces the bare `assert`s the session layer
    used to have (asserts vanish under ``python -O`` and attribute
    nothing)."""

    def __init__(self, party: str, step: str, kind: str,
                 detail: str = ""):
        self.party = party
        self.step = step
        self.kind = kind
        self.detail = detail
        super().__init__(
            f"[party={party} step={step} kind={kind}]"
            + (f" {detail}" if detail else ""))

    def retryable(self) -> bool:
        return self.kind in RETRYABLE_KINDS


class Deadline:
    """Monotonic time budget shared by a sequence of blocking calls.

    `None` seconds means unbounded (remaining() returns None); an
    expired deadline makes the next blocking call fail immediately
    instead of granting it a fresh per-call timeout.
    """

    __slots__ = ("_end",)

    def __init__(self, seconds: Optional[float]):
        self._end = (None if seconds is None
                     else time.monotonic() + seconds)

    def remaining(self) -> Optional[float]:
        if self._end is None:
            return None
        return max(0.0, self._end - time.monotonic())

    def expired(self) -> bool:
        rem = self.remaining()
        return rem is not None and rem <= 0.0


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    try:
        return float(raw)
    except ValueError:
        raise ValueError(f"{name} must be a number, got {raw!r}")


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    try:
        return int(raw)
    except ValueError:
        raise ValueError(f"{name} must be an integer, got {raw!r}")


@dataclass
class SessionConfig:
    """Timeout/retry levers (env forms in USAGE.md's lever table).

    `exchange_timeout` bounds ONE blocking send/recv; `round_deadline`
    is the whole-round budget the collector threads through every
    exchange of a round (a compile-heavy first round legitimately
    takes minutes on a cold cache — the defaults leave room for that;
    the fault tests shrink them to seconds).
    """

    connect_timeout: float = 60.0     # accept()/create_connection
    exchange_timeout: float = 600.0   # one send/recv on a channel
    ack_timeout: float = 60.0         # upload-ack window (marshaling
    #                                   is cheap next to prep compile)
    round_deadline: float = 1800.0    # budget for one whole round
    shutdown_timeout: float = 30.0    # proc.wait at close()
    retries: int = 2                  # extra attempts per exchange
    backoff: float = 0.25             # base of the exponential backoff

    @classmethod
    def from_env(cls) -> "SessionConfig":
        exchange = _env_float("MASTIC_SESSION_TIMEOUT", 600.0)
        return cls(
            connect_timeout=min(60.0, exchange),
            exchange_timeout=exchange,
            ack_timeout=min(60.0, exchange),
            round_deadline=_env_float("MASTIC_ROUND_DEADLINE", 1800.0),
            shutdown_timeout=min(30.0, exchange),
            retries=_env_int("MASTIC_SESSION_RETRIES", 2),
            backoff=_env_float("MASTIC_RETRY_BACKOFF", 0.25),
        )

    def child_env(self) -> dict:
        """Env overrides that make spawned party processes obey this
        config (they rebuild it with from_env)."""
        return {
            "MASTIC_SESSION_TIMEOUT": str(self.exchange_timeout),
            "MASTIC_ROUND_DEADLINE": str(self.round_deadline),
            "MASTIC_SESSION_RETRIES": str(self.retries),
            "MASTIC_RETRY_BACKOFF": str(self.backoff),
        }


class Channel:
    """Framed messages over a socket, every call deadline-bounded.

    `remote` names the peer for error attribution ("leader", "helper",
    "collector"); `injector` (drivers/faults.py) mutates outbound
    frames when the MASTIC_FAULTS lever is armed.  Framing matches
    `wire.frame`: 4-byte LE length prefix.

    `transport` (ISSUE 11, `mastic_tpu/net/transport.py`) owns HOW a
    framed byte string reaches the socket: None is the plain inline
    sendall; a `ShapedTransport` paces every frame by the configured
    bandwidth/RTT/jitter (`MASTIC_NET_SHAPE`) and fires the
    `net_send` fault checkpoint — network-separated parties over a
    link with wide-area realism.  `sent_bytes`/`recv_bytes` count
    wire traffic either way (the crossover bench reads them).
    """

    def __init__(self, sock: socket.socket, remote: str,
                 timeout: float = 600.0, injector=None,
                 transport=None):
        self.sock = sock
        self.remote = remote
        self.timeout = timeout
        self.injector = injector
        self.transport = transport
        self.sent_bytes = 0
        self.recv_bytes = 0
        # Blocking sockets with per-call settimeout; disable Nagle so
        # small protocol messages don't wait on the ack clock.
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            self._note_best_effort("setsockopt")

    # -- plumbing --------------------------------------------------

    def _note_best_effort(self, what: str) -> None:
        """Best-effort socket options may fail on exotic transports
        (AF_UNIX socketpairs in the tests); record, don't fail."""
        self._best_effort_failure = what

    def _budget(self, deadline: Optional[Deadline], step: str,
                timeout: Optional[float] = None) -> float:
        per_call = self.timeout if timeout is None else timeout
        if deadline is None:
            return per_call
        rem = deadline.remaining()
        if rem is None:
            return per_call
        if rem <= 0.0:
            raise SessionError(self.remote, step, KIND_TIMEOUT,
                               "session deadline exhausted")
        return min(rem, per_call)

    def _recv_exact(self, n: int, step: str,
                    deadline: Optional[Deadline],
                    timeout: Optional[float] = None) -> bytes:
        buf = bytearray()
        while len(buf) < n:
            self.sock.settimeout(
                self._budget(deadline, step, timeout))
            try:
                chunk = self.sock.recv(n - len(buf))
            except socket.timeout:
                raise SessionError(
                    self.remote, step, KIND_TIMEOUT,
                    f"no data for {self.timeout:.1f}s "
                    f"({len(buf)}/{n} bytes of the current frame)")
            except OSError as exc:
                raise SessionError(self.remote, step, KIND_CLOSED,
                                   f"socket error: {exc}")
            if not chunk:
                raise SessionError(
                    self.remote, step, KIND_CLOSED,
                    f"connection closed mid-frame "
                    f"({len(buf)}/{n} bytes)")
            buf += chunk
            self.recv_bytes += len(chunk)
        return bytes(buf)

    # -- framed messages -------------------------------------------

    def send_msg(self, payload: bytes, step: str = "send",
                 deadline: Optional[Deadline] = None) -> None:
        frames = [struct.pack("<I", len(payload)) + payload]
        if self.injector is not None:
            frames = self.injector.on_send(step, frames[0])
        for frame in frames:
            self.sock.settimeout(self._budget(deadline, step))
            try:
                if self.transport is not None:
                    self.transport.send(frame)
                else:
                    # The Channel is the transport seam BELOW the
                    # codec layer: every payload handed to send_msg
                    # is screened at its call site, which is where
                    # the whole-program SF004 rule fires.
                    self.sock.sendall(frame)
                self.sent_bytes += len(frame)
            except socket.timeout:
                raise SessionError(self.remote, step, KIND_TIMEOUT,
                                   "send blocked past the deadline")
            except OSError as exc:
                raise SessionError(self.remote, step, KIND_CLOSED,
                                   f"send failed: {exc}")

    def recv_msg(self, step: str = "recv",
                 deadline: Optional[Deadline] = None,
                 timeout: Optional[float] = None
                 ) -> Optional[bytes]:
        """One framed message; None on clean EOF at a frame boundary
        (the peer closed between messages — a legal shutdown).
        `timeout` overrides the channel's per-call timeout for this
        read (e.g. the short upload-ack window vs the long round
        reply)."""
        budget = self._budget(deadline, step, timeout)
        self.sock.settimeout(budget)
        try:
            first = self.sock.recv(4)
        except socket.timeout:
            raise SessionError(self.remote, step, KIND_TIMEOUT,
                               f"no message for {budget:.1f}s")
        except OSError as exc:
            raise SessionError(self.remote, step, KIND_CLOSED,
                               f"socket error: {exc}")
        if not first:
            return None
        self.recv_bytes += len(first)
        header = first if len(first) == 4 else \
            first + self._recv_exact(4 - len(first), step, deadline,
                                     timeout)
        (length,) = struct.unpack("<I", header)
        return self._recv_exact(length, step, deadline, timeout)

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            self._note_best_effort("close")


def _make_transport(sock: socket.socket, shaper, injector):
    """Wrap a just-built channel socket in a shaped transport when a
    link shape is armed (None stays the plain inline path)."""
    if shaper is None:
        return None
    from ..net.transport import for_socket

    return for_socket(sock, shaper, injector)


def connect(host: str, port: int, remote: str, timeout: float,
            exchange_timeout: float, injector=None,
            shaper=None) -> Channel:
    """Deadline-bounded create_connection -> Channel.  `shaper` is a
    `net.transport.LinkShape` (MASTIC_NET_SHAPE) applied to this
    end's sends."""
    try:
        sock = socket.create_connection((host, port), timeout=timeout)
    except socket.timeout:
        raise SessionError(remote, "connect", KIND_TIMEOUT,
                           f"no connection to {host}:{port} within "
                           f"{timeout:.1f}s")
    except OSError as exc:
        raise SessionError(remote, "connect", KIND_CLOSED,
                           f"connect to {host}:{port} failed: {exc}")
    return Channel(sock, remote, exchange_timeout, injector,
                   transport=_make_transport(sock, shaper, injector))


def accept(server: socket.socket, remote: str, timeout: float,
           exchange_timeout: float, injector=None,
           shaper=None) -> Channel:
    """Deadline-bounded server.accept() -> Channel."""
    server.settimeout(timeout)
    try:
        (sock, _addr) = server.accept()
    except socket.timeout:
        raise SessionError(remote, "accept", KIND_TIMEOUT,
                           f"no connection within {timeout:.1f}s")
    except OSError as exc:
        raise SessionError(remote, "accept", KIND_CLOSED,
                           f"accept failed: {exc}")
    return Channel(sock, remote, exchange_timeout, injector,
                   transport=_make_transport(sock, shaper, injector))


def with_retries(fn: Callable, attempts: int, backoff: float,
                 on_retry: Optional[Callable] = None,
                 deadline: Optional[Deadline] = None,
                 event: str = "session_retry",
                 extra: Optional[Callable] = None):
    """Run `fn()` with up to `attempts` retries on retryable
    SessionErrors, sleeping backoff * 2^i between attempts.
    `on_retry(err, attempt)` observes each retry (the metrics
    counters hook in here).

    With a `deadline`, the backoff sleep is clamped to the remaining
    budget, and an exhausted budget fails fast with the last error's
    attribution instead of sleeping through it — previously the loop
    slept the FULL exponential backoff even when the deadline had
    less remaining, so a caller's bounded operation could overrun
    its budget by up to the whole backoff ladder.

    Telemetry (ISSUE 7): every retry lands as a span event carrying
    the cause (party/step/kind/detail), the backoff actually slept
    and the remaining deadline budget — previously the cause was
    handed to `on_retry` and then LOST unless that callback kept it;
    the trace now shows the whole chain (tests/test_faults.py asserts
    it for an injected-fault round).  An exhausted budget emits
    `<event>_exhausted` before the attributed failure.

    `event` names the span event (ISSUE 14 satellite): protocol
    retries emit the default ``session_retry``; the reliable
    transport's redial ladder passes ``session_reconnect`` so traces
    separate transport recovery from protocol retry — `extra()` then
    contributes the transport's redial/replay attribution (e.g.
    `frames_replayed`) to every emitted event.  Only protocol
    retries feed the `mastic_session_retries_total` /
    `_timeouts_total` series; completed reconnects have their own
    counters, incremented by the channel when the link is back."""
    attempt = 0
    while True:
        try:
            return fn()
        except SessionError as err:
            if not err.retryable() or attempt >= attempts:
                raise
            pause = backoff * (2 ** attempt)
            rem = (deadline.remaining() if deadline is not None
                   else None)
            fields = dict(extra()) if extra is not None else {}
            if rem is not None:
                if rem <= 0.0:
                    obs_trace.event(
                        f"{event}_exhausted",
                        party=err.party, step=err.step,
                        kind=err.kind, attempts=attempt + 1,
                        **fields)
                    raise SessionError(
                        err.party, err.step, KIND_TIMEOUT,
                        f"retry budget exhausted after "
                        f"{attempt + 1} attempt(s); last error: "
                        f"[{err.kind}] {err.detail}")
                pause = min(pause, rem)
            obs_trace.event(
                event, party=err.party, step=err.step,
                kind=err.kind, detail=err.detail[:200],
                attempt=attempt + 1, backoff_s=round(pause, 4),
                deadline_remaining_s=(None if rem is None
                                      else round(rem, 3)),
                **fields)
            if event == "session_retry":
                get_registry().counter(
                    "mastic_session_retries_total", tenant="").inc()
                if err.kind == KIND_TIMEOUT:
                    get_registry().counter(
                        "mastic_session_timeouts_total",
                        tenant="").inc()
            if on_retry is not None:
                on_retry(err, attempt)
            time.sleep(pause)
            attempt += 1


# ---------------------------------------------------------------------
# Reconnect-and-replay sessions (ISSUE 14): the Channel API over the
# reliable TCP/mTLS transport.
# ---------------------------------------------------------------------

class ReliableChannel:
    """Channel-compatible framing over `net.transport.TcpTransport`:
    every payload rides a sequence-numbered, acked, replay-buffered
    frame, so a dropped connection or a healed partition costs a
    redial — never the round.

    On a dead link the channel redials through `with_retries`
    (exponential backoff, clamped to the caller's round `Deadline`,
    `session_reconnect` span events) and resumes from the last acked
    frame; the peer's `recv_next` cursor discards replayed duplicates,
    so delivery after any number of reconnects is exactly-once and a
    disturbed collection is bit-identical to an undisturbed one.
    Recovery is attributed: `reconnects` / `replayed_frames` feed
    `RoundMetrics` and the `mastic_session_reconnects_total` /
    `mastic_frames_replayed_total` series.

    A recv TIMEOUT does not redial — a peer deep in a prep compile is
    slow, not gone; only a dead socket (EOF, reset, refused) enters
    the reconnect path.  `shutdown` sends are fire-and-forget: the
    peer may already be gone, and redialing to deliver a goodbye
    would invert the teardown contract."""

    def __init__(self, transport, remote: str,
                 config: "SessionConfig"):
        self.tp = transport
        self.remote = remote
        self.config = config
        self.timeout = config.exchange_timeout
        self._established_once = False

    # -- Channel-API surface ---------------------------------------

    @property
    def sent_bytes(self) -> int:
        return self.tp.bytes_sent

    @property
    def recv_bytes(self) -> int:
        return self.tp.bytes_received

    @property
    def reconnects(self) -> int:
        return self.tp.reconnects

    @property
    def replayed_frames(self) -> int:
        return self.tp.replayed_frames

    def close(self) -> None:
        self.tp.close()

    # -- connection management -------------------------------------

    def _budget(self, deadline: Optional[Deadline], step: str,
                timeout: Optional[float] = None) -> float:
        per_call = self.timeout if timeout is None else timeout
        if deadline is None:
            return per_call
        rem = deadline.remaining()
        if rem is None:
            return per_call
        if rem <= 0.0:
            raise SessionError(self.remote, step, KIND_TIMEOUT,
                               "session deadline exhausted")
        return min(rem, per_call)

    def ensure_connected(self,
                         deadline: Optional[Deadline] = None,
                         step: str = "connect") -> None:
        if not self.tp.connected():
            self._reconnect(deadline, step)

    def _reconnect(self, deadline: Optional[Deadline],
                   step: str) -> None:
        """Redial (or re-accept) + resume, under the caller's
        deadline, with `session_reconnect` events per failed attempt
        and one summary event once the link is back."""
        from ..net.transport import RECONNECT_ATTEMPTS

        tp = self.tp
        first = not self._established_once

        def attempt():
            budget = self._budget(deadline, step,
                                  self.config.connect_timeout)
            return tp.establish(handshake_timeout=budget)

        replayed = with_retries(
            attempt, RECONNECT_ATTEMPTS, self.config.backoff,
            deadline=deadline, event="session_reconnect",
            extra=lambda: {"remote": self.remote,
                           "frames_replayed": tp.replayed_frames})
        self._established_once = True
        if first:
            return
        tp.reconnects += 1
        obs_trace.event(
            "session_reconnect", party=self.remote, step=step,
            kind="resumed", gen=tp.gen, redials=tp.reconnects,
            frames_replayed_now=replayed,
            frames_replayed=tp.replayed_frames)
        get_registry().counter("mastic_session_reconnects_total",
                               tenant="").inc()
        if replayed:
            get_registry().counter("mastic_frames_replayed_total",
                                   tenant="").inc(replayed)

    # -- framed messages -------------------------------------------

    def send_msg(self, payload: bytes, step: str = "send",
                 deadline: Optional[Deadline] = None) -> None:
        tp = self.tp
        if step == "shutdown":
            try:
                if tp.connected():
                    seq = tp.buffer_payload(payload)
                    tp.push(seq, self._budget(deadline, step))
            except (OSError, socket.timeout) as exc:
                raise SessionError(self.remote, step, KIND_CLOSED,
                                   f"send failed: {exc}")
            return
        seq = tp.buffer_payload(payload)
        # The fault seam fires with the frame already in the replay
        # buffer: an injected conn_drop/partition recovers through
        # reconnect-and-replay, never by losing the frame.
        tp.apply_net_fault(step)
        while True:
            self.ensure_connected(deadline, step)
            try:
                tp.push(seq, self._budget(deadline, step))
                return
            except socket.timeout:
                raise SessionError(self.remote, step, KIND_TIMEOUT,
                                   "send blocked past the deadline")
            except OSError:
                tp.kill_socket()   # dead link: redial and replay

    def recv_msg(self, step: str = "recv",
                 deadline: Optional[Deadline] = None,
                 timeout: Optional[float] = None
                 ) -> Optional[bytes]:
        tp = self.tp
        while True:
            self.ensure_connected(deadline, step)
            budget = self._budget(deadline, step, timeout)
            try:
                payload = tp.pull(budget)
            except socket.timeout:
                raise SessionError(self.remote, step, KIND_TIMEOUT,
                                   f"no message for {budget:.1f}s")
            except OSError:
                tp.kill_socket()   # dead link: redial, peer replays
                continue
            if payload is not None:
                return payload


def reliable_connect(host: str, port: int, remote: str,
                     config: SessionConfig, tls=None, injector=None,
                     shaper=None,
                     deadline: Optional[Deadline] = None
                     ) -> ReliableChannel:
    """Dial a party's reliable listener: fresh session id, mTLS when
    `tls` is armed (a `net.transport.TlsConfig` expecting `remote`'s
    certified name), reconnect-and-replay owned by the returned
    channel for the rest of the session."""
    from ..net.transport import TcpTransport, tcp_dial

    tls_for_peer = tls.expecting(remote) if tls is not None else None

    def dial():
        return tcp_dial(host, port, remote, config.connect_timeout,
                        tls=tls_for_peer, injector=injector)

    tp = TcpTransport(dial, remote, injector=injector, shape=shaper,
                      session_id=os.urandom(8))
    chan = ReliableChannel(tp, remote, config)
    chan.ensure_connected(deadline, "connect")
    return chan


def reliable_accept(listener, remote: str, config: SessionConfig,
                    injector=None, shaper=None,
                    deadline: Optional[Deadline] = None,
                    restart=None) -> ReliableChannel:
    """The accept side of a reliable link: the retained
    `net.transport.TcpListener` re-authenticates every (re)dial; the
    transport adopts the dialer's session id on first RESUME.  A
    `net.transport.SessionRestart` (`restart`) seeds the channel
    with the live socket and already-consumed RESUME of a peer that
    opened a NEW session, so a server loop hands over without losing
    the connection."""
    from ..net.transport import TcpTransport

    def reaccept():
        return listener.accept(remote, config.connect_timeout)

    adopt = None
    session_id = None
    if restart is not None:
        session_id = restart.session_id
        adopt = (restart.sock, restart.session_id, restart.gen,
                 restart.recv_next)
    tp = TcpTransport(reaccept, remote, injector=injector,
                      shape=shaper, session_id=session_id,
                      accept_side=True, adopt=adopt)
    chan = ReliableChannel(tp, remote, config)
    chan.ensure_connected(deadline, "accept")
    return chan
