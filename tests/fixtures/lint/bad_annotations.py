"""Known-bad: public function missing annotations (lint check 3)."""


def exposed(value, other):
    return value + other
