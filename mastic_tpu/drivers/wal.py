"""Durable write-ahead admission log (ISSUE 18; USAGE.md
"Durability").

The collector's exactly-once ingest contract used to hang off
snapshot-before-ack: every 2xx waited for a FULL service snapshot —
O(service-state) per report and a single write path with no disk-fault
story.  This module is the replacement durability substrate: an
append-only, segment-rotated WAL sits under admission, each acked
upload is one small checksummed record, fsync is batched (group
commit), and the service snapshot becomes a periodic COMPACTION
artifact rather than the ack path.

Record wire format (little-endian)::

    u32 payload_len | u32 crc32(payload) | payload
    payload = u64 seq | u8 kind | u16 tenant_len | tenant | blob

Kinds: ``KIND_REPORT`` (an admitted upload body, replayed through the
r15 ``CollectorService.submit()`` seam at recovery) and
``KIND_EPOCH_CUT`` (a scheduler epoch-cut marker, replayed via
``begin_epoch``).  ``seq`` is a monotone record number spanning
segment rotation, which is what the compaction covered-marker refers
to.

Durability policy (`MASTIC_WAL_FSYNC`):

* ``always`` — every append fsyncs inline before the ack releases;
* ``group:<ms>`` — appends enqueue on the current segment and a
  committer thread fsyncs once per interval, releasing every waiter
  that batch covered.  An ack NEVER precedes its record's fsync —
  the waiter blocks until the committer confirms (tested under an
  injected fsync delay).

Recovery (`AdmissionWal.recover`) scans segments in order, tolerating
a torn tail — a record whose header or payload runs past EOF is
truncated away and counted ``outcome="torn_tail"``, a full-length
record failing its CRC is skipped and counted ``outcome="corrupt"``;
recovery NEVER refuses.  Records at or below the covered marker's
``seq`` are skipped (``covered``) — but only when the marker's
recorded snapshot digest matches the snapshot actually restored;
otherwise the marker is distrusted and replay falls back to per-report
digest dedup against what the snapshot already buffers (``deduped``).

Failure is reason-coded, never silent: ENOSPC flips the log to the
``wal-full`` brownout, any other write/fsync error to
``wal-degraded`` — appends raise :class:`WalUnavailable` (the HTTP
front maps it to 503-with-Retry-After) and the next append attempts
revival by rotating to a fresh segment.

Everything here is stdlib-only (no jax import) so the network layer
can import the exception type for free.
"""

import json
import os
import re
import struct
import threading
import time
import zlib
from hashlib import sha256
from typing import Optional

from ..obs import trace as obs_trace
from ..obs.registry import get_registry
from ..obs.trace import get_tracer

KIND_REPORT = 1
KIND_EPOCH_CUT = 2
_KIND_NAMES = {KIND_REPORT: "report", KIND_EPOCH_CUT: "epoch_cut"}

_REC_HDR = struct.Struct("<II")       # payload_len, crc32(payload)
_PAYLOAD_HDR = struct.Struct("<QBH")  # seq, kind, tenant_len

_SEG_RE = re.compile(r"^wal-(\d{8})\.seg$")
_MARKER_NAME = "covered.json"

# Brownout reason codes (lint check 11: counted at the ingest front's
# shed sink, documented in USAGE.md "Durability").
REASON_WAL_FULL = "wal-full"
REASON_WAL_DEGRADED = "wal-degraded"

# Retry-After seconds a brownout 503 advertises: long enough to shed
# the hot retry loop, short enough that a transient fsync error heals
# within one client backoff step.
RETRY_AFTER_S = 1

# A group-commit waiter gives up after this long without its fsync —
# far past any sane group interval; hitting it means the committer
# died, which must surface as an attributed 503, not a hung ack.
_GROUP_WAIT_S = 30.0

# Per-append fsync-wait samples kept for stats() quantiles.
_SAMPLE_CAP = 8192


class WalUnavailable(RuntimeError):
    """Append could not be made durable.  `reason` is the brownout
    reason code (`wal-full` for ENOSPC, `wal-degraded` otherwise);
    the ingest plane maps this to a 503 with Retry-After and keeps
    serving reads/status — degradation is attributed, never silent."""

    def __init__(self, reason: str, retry_after: int = RETRY_AFTER_S):
        super().__init__(f"WAL unavailable: {reason}")
        self.reason = reason
        self.retry_after = retry_after


class WalConfig:
    """Durability levers (USAGE.md "Durability"): `MASTIC_WAL_FSYNC`
    (`always` | `group:<ms>`) and `MASTIC_WAL_SEGMENT_BYTES` (segment
    rotation bound)."""

    def __init__(self, fsync: str = "group", group_ms: float = 2.0,
                 segment_bytes: int = 8 * 1024 * 1024):
        if fsync not in ("always", "group"):
            raise ValueError(f"unknown WAL fsync policy {fsync!r} "
                             f"(want always or group)")
        if group_ms <= 0:
            raise ValueError("group interval must be positive")
        if segment_bytes <= 0:
            raise ValueError("segment bound must be positive")
        self.fsync = fsync
        self.group_ms = float(group_ms)
        self.segment_bytes = int(segment_bytes)

    @classmethod
    def from_env(cls, env: Optional[dict] = None) -> "WalConfig":
        env = os.environ if env is None else env
        cfg = cls()
        spec = env.get("MASTIC_WAL_FSYNC", "").strip()
        if spec:
            if spec == "always":
                cfg = cls(fsync="always",
                          segment_bytes=cfg.segment_bytes)
            elif spec.startswith("group:"):
                cfg = cls(fsync="group",
                          group_ms=float(spec[len("group:"):]),
                          segment_bytes=cfg.segment_bytes)
            else:
                raise ValueError(
                    f"MASTIC_WAL_FSYNC={spec!r} (want always or "
                    f"group:<ms>)")
        seg = env.get("MASTIC_WAL_SEGMENT_BYTES", "").strip()
        if seg:
            cfg = cls(fsync=cfg.fsync, group_ms=cfg.group_ms,
                      segment_bytes=int(seg))
        return cfg


def fsync_dir(path: str) -> None:
    """fsync a directory so a just-renamed/created entry survives a
    power cut (the tail of the tmp → fsync → replace → fsync(dir)
    atomic-write sequence; RB006's good idiom)."""
    fd = os.open(path or ".", os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


class _Waiter:
    __slots__ = ("event", "error")

    def __init__(self):
        self.event = threading.Event()
        self.error: Optional[str] = None


class AdmissionWal:
    """The append-only admission log over one directory of
    ``wal-NNNNNNNN.seg`` segments plus the ``covered.json`` compaction
    marker.  Thread-safe: handler threads append concurrently; the
    scheduler thread marks coverage; one committer thread (group
    policy) owns the batched fsync."""

    def __init__(self, path: str, config: Optional[WalConfig] = None,
                 injector=None, registry=None, fresh: bool = False):
        self.path = path
        self._cfg = config or WalConfig.from_env()
        self._injector = injector
        self._registry = registry or get_registry()
        self._mu = threading.Lock()
        # Raw fd, not a buffered file object: every byte handed to
        # os.write is visible to a post-crash scan (no library buffer
        # between the record and the OS), and the open/write calls
        # stay out of the analyzer's blocking-under-lock set.
        self._fd: Optional[int] = None
        self._seg_fmt = os.path.join(path, "wal-{:08d}.seg")
        self._seg_path: Optional[str] = None
        self._seg_size = 0
        self._seg_last_seq: dict = {}   # segment path -> last seq in it
        self._pending: list = []        # group-commit waiters
        self._degraded: Optional[str] = None
        self._closed = False
        self._samples: list = []        # recent fsync-wait ms
        self._appends = 0
        os.makedirs(path, exist_ok=True)
        if fresh:
            for name in os.listdir(path):
                if _SEG_RE.match(name) or name == _MARKER_NAME:
                    os.remove(os.path.join(path, name))
            fsync_dir(path)
        existing = self._segment_names()
        self._seg_index = (
            int(_SEG_RE.match(existing[-1]).group(1)) + 1 if existing
            else 0)
        # Appends need a seq watermark; a fresh log starts at 0, an
        # existing one must be recover()ed first (which also replays).
        self._next_seq: Optional[int] = 0 if (fresh or not existing) \
            else None
        marker = self._read_marker()
        if marker is not None and self._next_seq is not None:
            self._next_seq = max(self._next_seq,
                                 int(marker.get("seq", -1)) + 1)
        self._committer: Optional[threading.Thread] = None
        if self._cfg.fsync == "group":
            self._committer = threading.Thread(
                target=self._committer_loop, daemon=True,
                name="wal-committer")
            self._committer.start()

    # -- append path -----------------------------------------------

    def append_report(self, tenant: str, blob: bytes) -> int:
        """Log one admitted upload body; returns its seq.  Blocks
        until the record is fsync-durable (inline or via the group
        committer) — the caller's ack must not outrun this return."""
        return self._append(KIND_REPORT, tenant, blob)

    def append_epoch_cut(self, tenant: str) -> int:
        """Log a scheduler epoch-cut marker for `tenant`."""
        return self._append(KIND_EPOCH_CUT, tenant, b"")

    def _append(self, kind: int, tenant: str, blob: bytes) -> int:
        t0 = time.monotonic()
        tenant_b = tenant.encode("utf-8")
        inj = self._injector
        with self._mu:
            if self._closed:
                raise WalUnavailable(REASON_WAL_DEGRADED)
            if self._next_seq is None:
                raise RuntimeError(
                    "append before recover() on an existing WAL dir — "
                    "recovery owns the seq watermark")
            if self._degraded is not None:
                self._revive_locked()
            if self._fd is None:
                self._guard_os_locked(self._open_segment_locked)
            elif self._seg_size >= self._cfg.segment_bytes:
                self._guard_os_locked(self._rotate_locked)
            seq = self._next_seq
            payload = _PAYLOAD_HDR.pack(seq, kind, len(tenant_b)) \
                + tenant_b + blob
            rec = _REC_HDR.pack(len(payload), zlib.crc32(payload)) \
                + payload
            after = None

            def write_record():
                nonlocal rec, after
                if inj is not None:
                    (rec, after) = inj.on_disk("wal_append", rec)
                view = memoryview(rec)
                while view:
                    view = view[os.write(self._fd, view):]

            self._guard_os_locked(write_record)
            if after == "kill":
                # short-write/torn-tail fault: the truncated bytes
                # reached the OS (raw os.write, no library buffer),
                # the process dies before fsync and before any ack —
                # recovery must truncate-and-count this tail.
                os._exit(17)
            self._next_seq = seq + 1
            self._seg_size += len(rec)
            self._seg_last_seq[self._seg_path] = seq
            seg_size = self._seg_size
            if self._cfg.fsync == "always":
                self._guard_os_locked(self._fsync_locked)
                waiter = None
            else:
                waiter = _Waiter()
                self._pending.append(waiter)
        if waiter is not None:
            if not waiter.event.wait(timeout=_GROUP_WAIT_S):
                raise WalUnavailable(REASON_WAL_DEGRADED)
            if waiter.error is not None:
                raise WalUnavailable(waiter.error)
        if inj is not None:
            # kill-after-fsync-before-ack: the record is durable but
            # the client never sees the 2xx — recovery replays it and
            # the client's retry must dedup, not duplicate.
            inj.checkpoint("wal_ack")
        wait_ms = (time.monotonic() - t0) * 1000.0
        with self._mu:
            self._appends += 1
            self._samples.append(wait_ms)
            if len(self._samples) > _SAMPLE_CAP:
                del self._samples[:len(self._samples) - _SAMPLE_CAP]
        self._registry.counter("mastic_wal_appends_total",
                               tenant=tenant,
                               kind=_KIND_NAMES[kind]).inc()
        self._registry.histogram("mastic_wal_fsync_ms").observe(
            wait_ms)
        self._registry.gauge("mastic_wal_segment_bytes").set(seg_size)
        get_tracer().record_span("wal.append", duration_ms=wait_ms,
                                 tenant=tenant,
                                 kind=_KIND_NAMES[kind], seq=seq)
        return seq

    def _guard_os_locked(self, op) -> None:
        """Run one OS-touching step; an OSError flips the log to the
        reason-coded brownout and surfaces as WalUnavailable."""
        try:
            op()
        except OSError as err:
            reason = self._set_degraded_locked(err)
            raise WalUnavailable(reason) from err

    def _set_degraded_locked(self, err: OSError) -> str:
        import errno as _errno
        reason = (REASON_WAL_FULL
                  if err.errno == _errno.ENOSPC else
                  REASON_WAL_DEGRADED)
        self._degraded = reason
        obs_trace.event("wal_degraded", reason=reason,
                        error=str(err))
        return reason

    def _revive_locked(self) -> None:
        """A degraded log heals by rotating to a fresh segment (a
        later write may succeed where the wedged fd cannot — and for
        real ENOSPC the rotation itself keeps failing, so the 503
        brownout persists honestly)."""
        reason = self._degraded

        def reopen():
            self._rotate_locked()

        try:
            reopen()
        except OSError as err:
            self._set_degraded_locked(err)
            raise WalUnavailable(self._degraded or reason) from err
        self._degraded = None
        obs_trace.event("wal_recovered_from_degraded", reason=reason)

    def _open_segment_locked(self) -> None:
        path = self._seg_fmt.format(self._seg_index)
        self._seg_index += 1
        self._fd = os.open(path,
                           os.O_CREAT | os.O_WRONLY | os.O_APPEND,
                           0o644)
        self._seg_path = path
        self._seg_size = os.fstat(self._fd).st_size
        fsync_dir(self.path)

    def _rotate_locked(self) -> None:
        """Seal the current segment (commit anything pending on it)
        and open the next — every record lives wholly in one file."""
        if self._fd is not None:
            self._fsync_locked()
            self._release_pending_locked(None)
            os.close(self._fd)
            self._fd = None
        self._open_segment_locked()

    def _fsync_locked(self) -> None:
        if self._injector is not None:
            self._injector.on_disk("wal_fsync", b"")
        os.fsync(self._fd)

    def _release_pending_locked(self, error: Optional[str]) -> None:
        waiters = self._pending
        self._pending = []
        for w in waiters:
            w.error = error
            w.event.set()

    def _committer_loop(self) -> None:
        interval = self._cfg.group_ms / 1000.0
        while True:
            time.sleep(interval)
            with self._mu:
                if self._closed:
                    self._release_pending_locked(REASON_WAL_DEGRADED)
                    return
                if not self._pending or self._fd is None:
                    continue
                try:
                    self._fsync_locked()
                    self._release_pending_locked(None)
                except OSError as err:
                    reason = self._set_degraded_locked(err)
                    self._release_pending_locked(reason)

    # -- recovery ---------------------------------------------------

    def recover(self, service, snapshot_sha256: Optional[str] = None) \
            -> dict:
        """Scan every segment and replay what the restored snapshot
        does not cover; returns the outcome counts plus recovery wall
        time.  Never refuses: torn tails are truncated and counted,
        CRC failures skipped and counted.  `snapshot_sha256` is the
        digest of the snapshot bytes actually restored — the covered
        marker is honored only if it names the same digest (satellite:
        re-verify the snapshot before preferring it over replay)."""
        t0 = time.monotonic()
        counts = {"replayed": 0, "covered": 0, "deduped": 0,
                  "torn_tail": 0, "corrupt": 0, "epoch_cut": 0,
                  "rejected": 0}
        marker = self._read_marker()
        covered_seq = -1
        if marker is not None:
            if snapshot_sha256 is not None and \
                    marker.get("snapshot_sha256") == snapshot_sha256:
                covered_seq = int(marker.get("seq", -1))
            else:
                obs_trace.event("wal_marker_distrusted",
                                marker_seq=marker.get("seq"))
        next_seq = covered_seq + 1
        baseline: dict = {}
        for name in self._segment_names():
            seg = os.path.join(self.path, name)
            (records, good_len, tail) = self._scan_segment(seg)
            size = os.path.getsize(seg)
            if tail == "torn" and good_len < size:
                os.truncate(seg, good_len)
                fsync_dir(self.path)
                counts["torn_tail"] += 1
            counts["corrupt"] += sum(
                1 for r in records if r is None)
            for rec in records:
                if rec is None:
                    continue
                (seq, kind, tenant, blob) = rec
                next_seq = max(next_seq, seq + 1)
                self._seg_last_seq[seg] = seq
                if seq <= covered_seq:
                    counts["covered"] += 1
                    continue
                if tenant not in getattr(service, "tenants", {}):
                    counts["rejected"] += 1
                    continue
                if kind == KIND_EPOCH_CUT:
                    service.begin_epoch(tenant)
                    counts["epoch_cut"] += 1
                    continue
                digest = sha256(blob).digest()
                if tenant not in baseline:
                    baseline[tenant] = service.report_digests(tenant)
                if digest in baseline[tenant]:
                    # Double-covered: the snapshot already buffers
                    # this report (stale/distrusted marker) — ack
                    # idempotently on retry, do not re-buffer.
                    service.note_replayed(tenant, digest)
                    counts["deduped"] += 1
                    continue
                (status, _detail) = service.submit(tenant, blob)
                service.note_replayed(tenant, digest)
                baseline[tenant].add(digest)
                if status in ("admitted", "queued"):
                    counts["replayed"] += 1
                else:
                    counts["rejected"] += 1
        self._next_seq = max(next_seq, 0)
        for (outcome, n) in counts.items():
            if n:
                self._registry.counter(
                    "mastic_wal_recovered_records_total",
                    outcome=outcome).inc(n)
        wall_ms = (time.monotonic() - t0) * 1000.0
        get_tracer().record_span("wal.recover", duration_ms=wall_ms,
                                 **counts)
        stats = dict(counts)
        stats["recovery_wall_ms"] = wall_ms
        stats["next_seq"] = self._next_seq
        return stats

    def _scan_segment(self, path: str):
        """Parse one segment.  Returns (records, good_len, tail)
        where records holds (seq, kind, tenant, blob) tuples — None
        for a full-length record whose CRC failed (bit-flip
        post-checksum: detected, attributed, skipped) — good_len is
        the byte offset of the torn tail (== file size when clean)
        and tail is "torn" or None."""
        with open(path, "rb") as f:
            data = f.read()
        records: list = []
        off = 0
        while off < len(data):
            if len(data) - off < _REC_HDR.size:
                return (records, off, "torn")
            (plen, crc) = _REC_HDR.unpack_from(data, off)
            start = off + _REC_HDR.size
            if len(data) - start < plen:
                return (records, off, "torn")
            payload = data[start:start + plen]
            off = start + plen
            if zlib.crc32(payload) != crc or \
                    plen < _PAYLOAD_HDR.size:
                records.append(None)
                continue
            (seq, kind, tlen) = _PAYLOAD_HDR.unpack_from(payload, 0)
            body = payload[_PAYLOAD_HDR.size:]
            if len(body) < tlen or kind not in _KIND_NAMES:
                records.append(None)
                continue
            tenant = body[:tlen].decode("utf-8", "replace")
            records.append((seq, kind, tenant, body[tlen:]))
        return (records, off, None)

    # -- compaction -------------------------------------------------

    def tail_seq(self) -> int:
        """Highest seq appended so far (-1 when empty) — capture this
        BEFORE serializing a snapshot: every record at or below it is
        in the snapshot, so covering less than reality stays safe."""
        with self._mu:
            return (self._next_seq or 0) - 1

    def mark_covered(self, seq: int, snapshot_sha256: str) -> int:
        """Record that a durable snapshot (of the given digest) covers
        every record with seq <= `seq`, then delete the segments that
        are wholly covered.  Returns the number of segments dropped."""
        marker = {"seq": int(seq), "snapshot_sha256": snapshot_sha256}
        tmp = os.path.join(self.path, _MARKER_NAME + ".tmp")
        with open(tmp, "w") as f:
            json.dump(marker, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.path, _MARKER_NAME))
        fsync_dir(self.path)
        dropped = 0
        candidates = [os.path.join(self.path, name)
                      for name in self._segment_names()]
        with self._mu:
            current = self._seg_path
            for seg in candidates:
                if seg == current:
                    continue
                last = self._seg_last_seq.get(seg)
                if last is None:
                    (records, _len, _tail) = self._scan_segment(seg)
                    real = [r for r in records if r is not None]
                    last = real[-1][0] if real else -1
                if last <= seq:
                    os.remove(seg)
                    dropped += 1
                    self._seg_last_seq.pop(seg, None)
        if dropped:
            fsync_dir(self.path)
            obs_trace.event("wal_compacted", dropped=dropped,
                            covered_seq=int(seq))
        return dropped

    # -- bookkeeping ------------------------------------------------

    def _segment_names(self) -> list:
        return sorted(n for n in os.listdir(self.path)
                      if _SEG_RE.match(n))

    def _read_marker(self) -> Optional[dict]:
        try:
            with open(os.path.join(self.path, _MARKER_NAME)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def stats(self) -> dict:
        """Append/fsync accounting for benches and drill JSON."""
        with self._mu:
            samples = sorted(self._samples)
            appends = self._appends
            degraded = self._degraded

        def pct(p: float) -> Optional[float]:
            if not samples:
                return None
            i = min(len(samples) - 1, int(p * (len(samples) - 1)))
            return samples[i]

        return {"appends": appends,
                "fsync_wait_ms_p50": pct(0.50),
                "fsync_wait_ms_p99": pct(0.99),
                "segments": len(self._segment_names()),
                "degraded": degraded}

    def close(self) -> None:
        with self._mu:
            if self._closed:
                return
            self._closed = True
            if self._fd is not None:
                try:
                    self._fsync_locked()
                    self._release_pending_locked(None)
                except OSError:
                    self._release_pending_locked(REASON_WAL_DEGRADED)
                os.close(self._fd)
                self._fd = None
