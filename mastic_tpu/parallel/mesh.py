"""Mesh construction and sharded Mastic rounds (pjit / GSPMD).

Sharding layout:
  * report-indexed arrays (nonces, keys, correction words, out shares):
    P("reports") on the leading axis;
  * (report x node) grids (seeds, ctrls, payloads, proofs):
    P("reports", "nodes") — the node axis is the sequence-parallel-like
    axis; within-level node grids are wide (the candidate-prefix
    frontier), so sharding them over chips covers the reference's
    "parallel over candidate prefixes" axis (SURVEY.md §2.3);
  * aggregate shares: replicated output of an all-reduce that XLA
    derives from the masked sum over the sharded report axis (psum
    over ICI; reference agg_update, mastic.py:384-397).

All functions jit once per (shape, level-schedule) and are reused
across levels/rounds.
"""

from typing import Optional

import jax

import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..backend.mastic_jax import BatchedMastic
from ..backend.vidpf_jax import EvalState


def make_mesh(n_devices: Optional[int] = None,
              nodes_axis: int = 1) -> Mesh:
    """A ("reports", "nodes") mesh over the first `n_devices` devices.
    `nodes_axis` devices are assigned to the node (prefix-grid) axis,
    the rest to reports."""
    devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    if n_devices % nodes_axis != 0:
        raise ValueError("nodes_axis must divide n_devices")
    devs = np.asarray(devices[:n_devices]).reshape(
        n_devices // nodes_axis, nodes_axis)
    return Mesh(devs, ("reports", "nodes"))


def shard_batch(mesh: Mesh, array: jax.Array,
                node_axis: Optional[int] = None) -> jax.Array:
    """Place a report-batched array: leading axis over "reports",
    `node_axis` (if given) over "nodes", rest replicated."""
    spec = [None] * array.ndim
    spec[0] = "reports"
    if node_axis is not None:
        spec[node_axis] = "nodes"
    return jax.device_put(
        array, NamedSharding(mesh, P(*spec)))


def install_grid_sharding(bm: BatchedMastic, mesh: Mesh) -> None:
    """Keep every level's (reports x nodes) eval grid distributed over
    both mesh axes (seed/proof carry a trailing byte axis, w two
    trailing limb axes)."""

    def constrain(state: EvalState) -> EvalState:
        def c(x):
            spec = ["reports", "nodes"] + [None] * (x.ndim - 2)
            return jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, P(*spec)))

        return EvalState(seed=c(state.seed), ctrl=c(state.ctrl),
                         w=c(state.w), proof=c(state.proof))

    bm.vidpf.constrain_state = constrain


def sharded_prep_fn(bm: BatchedMastic, mesh: Mesh, agg_id: int,
                    verify_key: bytes, ctx: bytes, agg_param):
    """Jit one aggregator's full prep over the mesh.

    Returns fn(nonces, cws, keys[, proof_shares | seeds][, peer_parts])
    -> BatchedPrep with report-sharded outputs.  The (reports x nodes)
    intermediates inside eval are sharded over both mesh axes via a
    sharding constraint on the root state.
    """
    rep = NamedSharding(mesh, P("reports"))

    def fn(nonces, cws, keys, proof_shares=None, seeds=None,
           peer_parts=None):
        nonces = jax.lax.with_sharding_constraint(nonces, rep)
        return bm.prep(agg_id, verify_key, ctx, agg_param, nonces, cws,
                       keys, proof_shares=proof_shares, seeds=seeds,
                       peer_jr_parts=peer_parts)

    return jax.jit(fn)


def sharded_round_fn(bm: BatchedMastic, mesh: Mesh, verify_key: bytes,
                     ctx: bytes, agg_param):
    """Jit a full two-party simulated round over the mesh: both preps,
    every check — including the device FLP query/decide on
    weight-check rounds — and the masked aggregation whose sum over the
    sharded report axis lowers to an all-reduce (psum) across chips.

    Returns fn(batch: ReportBatch)
    -> (agg_share0, agg_share1, accept, ok).
    """
    rep = NamedSharding(mesh, P("reports"))
    out_rep = NamedSharding(mesh, P())

    def fn(batch):
        batch = batch._replace(
            nonces=jax.lax.with_sharding_constraint(batch.nonces, rep))
        return bm.round_device(verify_key, ctx, agg_param, batch)

    return jax.jit(fn, out_shardings=(out_rep, out_rep,
                                      NamedSharding(mesh, P("reports")),
                                      NamedSharding(mesh, P("reports"))))


def place_reports(mesh: Mesh, tree):
    """Place every array in a pytree with its leading (report) axis
    sharded over the mesh's "reports" axis, other axes replicated.
    None leaves pass through (optional batch fields)."""

    def put(x):
        if x is None:
            return None
        spec = ["reports"] + [None] * (x.ndim - 1)
        return jax.device_put(x, NamedSharding(mesh, P(*spec)))

    return jax.tree.map(put, tree, is_leaf=lambda x: x is None)


def place_replicated(mesh: Mesh, tree):
    """Place every array in a pytree fully replicated across the mesh
    (the small per-round inputs — verify key, gather index tensors —
    that every shard reads in full).  Pinning these explicitly keeps
    the AOT-compiled round programs' input shardings deterministic: a
    warm-compiled executable and the inline-lowered one agree on every
    argument's placement (drivers/pipeline.ProgramCache)."""
    repl = NamedSharding(mesh, P())

    def put(x):
        if x is None:
            return None
        return jax.device_put(x, repl)

    return jax.tree.map(put, tree, is_leaf=lambda x: x is None)


def shard_incremental_runner(runner, mesh: Mesh) -> None:
    """Make an incremental runner mesh-aware (SURVEY.md §7 step 7 for
    the production execution model): both aggregators' carries, the
    AES round keys and the correction-word arrays are sharded on the
    report axis, so every per-report op in agg_round runs purely
    locally and the only cross-chip traffic is the masked
    aggregation's sum over the sharded axis — which GSPMD lowers to an
    all-reduce (psum over ICI), exactly the reference's agg_update
    fold (mastic.py:384-397) distributed.

    Works for both _IncrementalRunner (resident batch) and
    ChunkedIncrementalRunner (per-chunk placement at upload time via
    runner.mesh)."""
    n_rep = mesh.shape["reports"]
    store = getattr(runner, "store", None)
    if store is None and runner.num_reports % n_rep != 0:
        # The resident batch IS the device tile — it must shard
        # evenly.  A chunked runner pads each chunk's device rows up
        # to the shard multiple instead and masks the dead lanes
        # (ChunkedIncrementalRunner._device_rows), so any chunk_size
        # works on any mesh.
        raise ValueError(
            f"report count {runner.num_reports} must be divisible by "
            f"the mesh's reports axis ({n_rep}) to shard evenly")
    runner.mesh = mesh
    # The jitted round closures bake the mesh's output shardings in
    # (RoundPrograms builds them with explicit out_shardings when a
    # mesh is installed), so attaching a mesh after construction must
    # rebind them; the AOT ProgramCache keys on the mesh shape, so
    # its entries simply stop being reachable.
    for name in ("_eval_fn", "_combine_fn"):
        if hasattr(runner, name):
            setattr(runner, name, None)
    if hasattr(runner, "_wc_fns"):
        runner._wc_fns = {}
    if getattr(runner, "carries", None) is not None:
        runner.carries = [place_reports(mesh, c)
                          for c in runner.carries]
    if getattr(runner, "batch", None) is not None:
        runner.batch = place_reports(mesh, runner.batch)
    for name in ("ext_rk", "conv_rk"):
        if getattr(runner, name, None) is not None:
            setattr(runner, name,
                    place_reports(mesh, getattr(runner, name)))


def sharded_gen_fn(bm: BatchedMastic, mesh: Mesh, ctx: bytes):
    """Jit batched client-side VIDPF key generation with reports
    sharded across the mesh (the client fleet axis)."""
    rep = NamedSharding(mesh, P("reports"))

    def fn(alphas, betas, nonces, rand):
        alphas = jax.lax.with_sharding_constraint(alphas, rep)
        return bm.vidpf.gen(alphas, betas, ctx, nonces, rand)

    return jax.jit(fn)
