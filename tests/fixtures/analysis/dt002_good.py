"""Known-good: the widening op is masked before narrowing (DT002)."""

import jax.numpy as jnp


def masked(v):
    return ((v << 4) & 0xFF).astype(jnp.uint8)
