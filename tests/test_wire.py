"""Wire-codec round trips and canonicality checks."""

import pytest

from mastic_tpu import MasticCount, MasticHistogram
from mastic_tpu.common import gen_rand


def test_public_share_round_trip():
    for mastic in (MasticCount(7), MasticHistogram(3, 4, 2)):
        vidpf = mastic.vidpf
        alpha = vidpf.test_index_from_int(5, vidpf.BITS)
        beta = [vidpf.field(i + 1) for i in range(vidpf.VALUE_LEN)]
        (cw, _keys) = vidpf.gen(alpha, beta, b"ctx", gen_rand(16),
                                gen_rand(vidpf.RAND_SIZE))
        encoded = vidpf.encode_public_share(cw)
        decoded = vidpf.decode_public_share(encoded)
        assert vidpf.encode_public_share(decoded) == encoded
        for (got, want) in zip(decoded, cw):
            assert got[0] == want[0]
            assert list(got[1]) == list(want[1])
            assert got[2] == want[2]
            assert got[3] == want[3]

        with pytest.raises(ValueError):
            vidpf.decode_public_share(encoded + b"\x00")


def test_agg_param_round_trip_and_canonicality():
    mastic = MasticCount(4)
    agg_param = (1, tuple(mastic.vidpf.test_index_from_int(v, 2)
                          for v in range(3)), True)
    encoded = mastic.encode_agg_param(agg_param)
    assert mastic.decode_agg_param(encoded) == agg_param

    # Nonzero padding bits in a prefix chunk must be rejected: the
    # encoding is injective on the wire (decode o encode = id).
    tampered = bytearray(encoded)
    tampered[6] |= 0x01  # low bit of the 2-bit prefix byte is padding
    with pytest.raises(ValueError):
        mastic.decode_agg_param(bytes(tampered))


def test_agg_param_level_zero():
    mastic = MasticCount(4)
    agg_param = (0, ((False,), (True,)), True)
    encoded = mastic.encode_agg_param(agg_param)
    assert mastic.decode_agg_param(encoded) == agg_param
