"""Fault tolerance of the process-separated aggregation sessions
(ISSUE 3): the injectable fault matrix, deadline bounds, quarantine,
and kill-and-resume bit-identity.

Fast tier: the channel-level fault matrix over in-process socketpairs
(every fault class, bounded structured outcomes in milliseconds), the
cheap subprocess faults (failures before any device compile), and the
headline kill-and-resume test.  Slow tier: the full-round subprocess
matrix (faults at prep/resolve/agg steps — each case pays a real
round's compile) and the joint-rand resume instance.

Run the fast tier via `make faults` (wired into `make ci`).
"""

import socket
import threading
import time

import pytest

from mastic_tpu.common import gen_rand
from mastic_tpu.drivers import faults
from mastic_tpu.drivers.parties import (AggregationSession,
                                        ProcessCollector,
                                        REASON_MALFORMED)
from mastic_tpu.drivers.session import (Channel, Deadline,
                                        SessionConfig, SessionError)
from mastic_tpu.mastic import MasticCount, MasticHistogram

CTX = b"fault matrix"

# Cheap-fault config: everything fails fast; no full round runs under
# this one.  shutdown_timeout stays small so close() of a party mid-
# compile terminates instead of waiting.
CFG_FAST = SessionConfig(connect_timeout=15.0, exchange_timeout=10.0,
                         ack_timeout=10.0, round_deadline=30.0,
                         shutdown_timeout=3.0, retries=1, backoff=0.1)
# Full-round config: the per-exchange window must cover a real prep
# compile on the CPU fabric (~1-2 min cold).
CFG_ROUND = SessionConfig(connect_timeout=30.0,
                          exchange_timeout=240.0, ack_timeout=60.0,
                          round_deadline=600.0, shutdown_timeout=5.0,
                          retries=1, backoff=0.2)


def _count_reports(m, alphas):
    reports = []
    for alpha in alphas:
        nonce = gen_rand(m.NONCE_SIZE)
        (ps, shares) = m.shard(CTX, (alpha, 1), nonce,
                               gen_rand(m.RAND_SIZE))
        reports.append((nonce, ps, shares))
    return reports


COUNT_SPEC = {"class": "MasticCount", "args": [2]}
COUNT_PARAM = (0, ((False,), (True,)), True)


# -- fault-spec parser -----------------------------------------------

def test_parse_faults():
    rules = faults.parse_faults(
        "kill:party=helper:step=round_start;"
        "corrupt:party=leader:step=prep_share:nth=2:xor=0x80:offset=6")
    assert [r.action for r in rules] == ["kill", "corrupt"]
    assert rules[0].party == "helper"
    assert rules[1].nth == 2 and rules[1].xor == 0x80 \
        and rules[1].offset == 6
    assert faults.parse_faults("") == []
    assert faults.parse_faults(None) == []


@pytest.mark.parametrize("bad", [
    "explode:party=leader:step=x",        # unknown action
    "drop:party=martian:step=x",          # unknown party
    "drop:step=x",                        # missing party
    "drop:party=leader",                  # missing step
    "drop:party=leader:step=x:zap=1",     # unknown key
])
def test_parse_faults_rejects(bad):
    with pytest.raises(ValueError):
        faults.parse_faults(bad)


def test_rules_fire_once_at_nth():
    inj = faults.FaultInjector(
        faults.parse_faults("drop:party=leader:step=s:nth=2"),
        "leader")
    frame = faults.frame_of(b"abc")
    assert inj.on_send("s", frame) == [frame]      # 1st: passes
    assert inj.on_send("s", frame) == []           # 2nd: dropped
    assert inj.on_send("s", frame) == [frame]      # fired, inert now


# -- channel-level fault matrix (in-process, socketpair) -------------

def _pair(spec=None, party="leader", rx_timeout=0.6):
    (a, b) = socket.socketpair()
    inj = (faults.FaultInjector(faults.parse_faults(spec), party)
           if spec else None)
    tx = Channel(a, "receiver", timeout=5.0, injector=inj)
    rx = Channel(b, party, timeout=rx_timeout)
    return (tx, rx)


def _send_async(tx, payload, step):
    def run():
        try:
            tx.send_msg(payload, step)
        except SessionError:
            return  # receiver gave up first — expected for stalls
    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t


def test_clean_channel_roundtrip():
    (tx, rx) = _pair()
    tx.send_msg(b"hello", "s")
    assert rx.recv_msg("s") == b"hello"
    tx.close()
    assert rx.recv_msg("s") is None    # clean EOF -> None
    rx.close()


def test_fault_drop_times_out_attributed():
    (tx, rx) = _pair("drop:party=leader:step=prep_share")
    tx.send_msg(b"payload", "prep_share")
    t0 = time.monotonic()
    with pytest.raises(SessionError) as ei:
        rx.recv_msg("prep_share")
    assert time.monotonic() - t0 < 3.0
    assert ei.value.kind == "timeout"
    assert ei.value.party == "leader"
    assert ei.value.step == "prep_share"


def test_fault_truncate_bounded():
    """A frame whose header promises more bytes than arrive leaves
    the receiver waiting — the deadline, not the peer, ends it."""
    (tx, rx) = _pair("truncate:party=leader:step=prep_share:cut=3")
    tx.send_msg(b"payload", "prep_share")
    t0 = time.monotonic()
    with pytest.raises(SessionError) as ei:
        rx.recv_msg("prep_share")
    assert time.monotonic() - t0 < 3.0
    assert ei.value.kind == "timeout"


def test_fault_corrupt_mutates_payload():
    (tx, rx) = _pair("corrupt:party=leader:step=prep_share:xor=0x80")
    tx.send_msg(b"payload", "prep_share")
    got = rx.recv_msg("prep_share")
    assert got != b"payload"
    assert got == bytes([b"p"[0] ^ 0x80]) + b"ayload"


def test_fault_duplicate_delivers_twice():
    (tx, rx) = _pair("duplicate:party=leader:step=prep_share")
    tx.send_msg(b"payload", "prep_share")
    assert rx.recv_msg("prep_share") == b"payload"
    assert rx.recv_msg("prep_share") == b"payload"


def test_fault_delay_within_deadline_arrives():
    (tx, rx) = _pair("delay:party=leader:step=prep_share:delay=0.2",
                     rx_timeout=2.0)
    t0 = time.monotonic()
    _send_async(tx, b"payload", "prep_share")
    assert rx.recv_msg("prep_share") == b"payload"
    assert time.monotonic() - t0 >= 0.2


@pytest.mark.parametrize("spec", [
    "delay:party=leader:step=prep_share:delay=30",
    "hang:party=leader:step=prep_share",
], ids=["delay-past-deadline", "hang"])
def test_fault_stall_times_out(spec):
    (tx, rx) = _pair(spec)
    t0 = time.monotonic()
    _send_async(tx, b"payload", "prep_share")
    with pytest.raises(SessionError) as ei:
        rx.recv_msg("prep_share")
    assert time.monotonic() - t0 < 3.0
    assert ei.value.kind == "timeout"
    assert ei.value.party == "leader"


def test_deadline_budget_is_shared():
    """An exhausted session deadline fails the next call immediately
    instead of granting it a fresh per-call timeout."""
    (_tx, rx) = _pair(rx_timeout=30.0)
    deadline = Deadline(0.05)
    time.sleep(0.1)
    t0 = time.monotonic()
    with pytest.raises(SessionError) as ei:
        rx.recv_msg("agg_share", deadline)
    assert time.monotonic() - t0 < 1.0
    assert ei.value.kind == "timeout"
    assert "deadline exhausted" in ei.value.detail


# -- subprocess faults that fail before any device compile -----------

def test_kill_at_spawn_attributed():
    """A party that dies before the handshake fails the session
    constructor in bounded time, attributed to the dead party."""
    m = MasticCount(2)
    t0 = time.monotonic()
    with pytest.raises(SessionError) as ei:
        ProcessCollector(m, COUNT_SPEC, CTX,
                         gen_rand(m.VERIFY_KEY_SIZE), config=CFG_FAST,
                         faults_spec="kill:party=helper:step=spawn")
    assert time.monotonic() - t0 < CFG_FAST.connect_timeout + 20
    assert ei.value.party == "helper"
    assert ei.value.kind == "crashed"
    assert f"rc={faults.KILL_EXIT_CODE}" in ei.value.detail


def test_hang_at_upload_times_out_attributed():
    """A party hanging before its upload ack fails upload() within
    the ack window, attributed with the step name."""
    m = MasticCount(2)
    reports = _count_reports(m, [(False, True), (True, False)])
    cfg = SessionConfig(connect_timeout=15.0, exchange_timeout=10.0,
                        ack_timeout=8.0, round_deadline=30.0,
                        shutdown_timeout=3.0, retries=0, backoff=0.1)
    coll = ProcessCollector(
        m, COUNT_SPEC, CTX, gen_rand(m.VERIFY_KEY_SIZE), config=cfg,
        faults_spec="hang:party=leader:step=reports_loaded")
    try:
        t0 = time.monotonic()
        with pytest.raises(SessionError) as ei:
            coll.upload(reports)
        assert time.monotonic() - t0 < 30
        assert ei.value.party == "leader"
        assert ei.value.step == "upload_ack"
        assert ei.value.kind == "timeout"
        assert coll.counters["timeouts"] >= 1
    finally:
        coll.close()


def test_dropped_upload_ack_is_retried():
    """A lost ack retries the (idempotent) upload; the stale-ack
    generation guard keeps the channel in sync, and the retry lands
    in the counters."""
    m = MasticCount(2)
    reports = _count_reports(m, [(False, True), (True, False)])
    coll = ProcessCollector(
        m, COUNT_SPEC, CTX, gen_rand(m.VERIFY_KEY_SIZE),
        config=CFG_FAST,
        faults_spec="drop:party=leader:step=upload_ack")
    try:
        coll.upload(reports)          # succeeds on the second attempt
        assert coll.counters["retries"] >= 1
        assert coll.counters["timeouts"] >= 1
        assert coll.counters["quarantined"] == 0
    finally:
        coll.close()


def test_retry_chain_lands_in_trace():
    """ISSUE 7 satellite: an injected-fault exchange's trace shows
    the whole retry chain — each `session_retry` event carries the
    cause (party/step/kind), the backoff actually slept, and the
    remaining round-deadline budget; previously with_retries handed
    the cause to on_retry and the chain was lost."""
    from mastic_tpu.obs import trace as obs_trace

    tracer = obs_trace.configure()   # fresh ring for this test
    try:
        m = MasticCount(2)
        reports = _count_reports(m, [(False, True), (True, False)])
        coll = ProcessCollector(
            m, COUNT_SPEC, CTX, gen_rand(m.VERIFY_KEY_SIZE),
            config=CFG_FAST,
            faults_spec="drop:party=leader:step=upload_ack")
        try:
            coll.upload(reports)
        finally:
            coll.close()
        retries = [sp for sp in tracer.spans()
                   if sp.name == "session_retry"]
        assert retries, [sp.name for sp in tracer.spans()]
        ev = retries[0].attrs
        assert ev["party"] == "leader"
        assert ev["step"] == "upload_ack"
        assert ev["kind"] == "timeout"
        assert ev["attempt"] == 1
        assert ev["backoff_s"] > 0
        # the upload's retry ladder shares the round deadline, so
        # the remaining budget is a real number, already spent down
        assert 0 < ev["deadline_remaining_s"] \
            <= CFG_FAST.round_deadline
    finally:
        obs_trace.configure()


def test_malformed_report_quarantined_not_fatal():
    """A truncated report blob quarantines that report with a reason
    code; the batch survives."""
    m = MasticCount(2)
    reports = _count_reports(m, [(False, True), (True, False),
                                 (True, True)])
    coll = ProcessCollector(
        m, COUNT_SPEC, CTX, gen_rand(m.VERIFY_KEY_SIZE),
        config=CFG_FAST,
        faults_spec="truncate:party=collector:step=upload_report:nth=2")
    try:
        coll.upload(reports)
        assert coll.quarantine == {1: REASON_MALFORMED}
        assert coll.counters["quarantined"] == 1
        assert list(coll.quarantine_mask()) == [False, True, False]
    finally:
        coll.close()


def test_all_reports_quarantined_is_refused():
    """Each party quarantines a DIFFERENT report (leader's copy of
    report 0, helper's of report 1) — individually survivable, but
    the union covers the whole batch, so the session refuses."""
    m = MasticCount(2)
    reports = _count_reports(m, [(False, True), (True, False)])
    coll = ProcessCollector(
        m, COUNT_SPEC, CTX, gen_rand(m.VERIFY_KEY_SIZE),
        config=CFG_FAST,
        faults_spec=("truncate:party=collector:step=upload_report:nth=1;"
                     "truncate:party=collector:step=upload_report:nth=4"))
    try:
        with pytest.raises(SessionError) as ei:
            coll.upload(reports)
        assert ei.value.kind == "protocol"
        assert "quarantined" in ei.value.detail
    finally:
        coll.close()


def test_wholly_malformed_upload_naks():
    """A party whose every report blob is malformed NAKs the upload
    as a structured error instead of aggregating nothing."""
    m = MasticCount(2)
    reports = _count_reports(m, [(False, True)])
    coll = ProcessCollector(
        m, COUNT_SPEC, CTX, gen_rand(m.VERIFY_KEY_SIZE),
        config=CFG_FAST,
        faults_spec="truncate:party=collector:step=upload_report:nth=1")
    try:
        with pytest.raises(SessionError) as ei:
            coll.upload(reports)
        assert ei.value.kind == "malformed"
        assert "malformed" in ei.value.detail
    finally:
        coll.close()


def test_corrupt_round_command_naks_fast():
    """A corrupted command byte is refused by the party with a
    structured NAK — attribution arrives immediately, not after the
    deadline."""
    m = MasticCount(2)
    reports = _count_reports(m, [(False, True), (True, False)])
    coll = ProcessCollector(
        m, COUNT_SPEC, CTX, gen_rand(m.VERIFY_KEY_SIZE),
        config=CFG_FAST,
        faults_spec="corrupt:party=collector:step=agg_param:offset=4:xor=16")
    try:
        coll.upload(reports)
        t0 = time.monotonic()
        with pytest.raises(SessionError) as ei:
            coll.round(COUNT_PARAM)
        # The NAK beats the round deadline by a wide margin.
        assert time.monotonic() - t0 < 15
        assert ei.value.kind == "protocol"
        assert ei.value.step == "command"
    finally:
        coll.close()


def test_snapshot_roundtrip_replays_upload():
    m = MasticCount(2)
    reports = _count_reports(m, [(False, True), (True, False)])
    sess = AggregationSession(m, COUNT_SPEC, CTX,
                              gen_rand(m.VERIFY_KEY_SIZE),
                              config=CFG_FAST)
    try:
        sess.upload(reports)
        blob = sess.to_bytes()
    finally:
        sess.close()
    sess2 = AggregationSession.from_bytes(blob, config=CFG_FAST)
    try:
        assert sess2.coll.num_reports == 2
        assert sess2.coll.quarantine == {}
        assert sess2.completed == []
    finally:
        sess2.close()


def test_snapshot_refuses_garbage():
    with pytest.raises(ValueError):
        AggregationSession.from_bytes(b"\xff" * 64)


# -- kill-and-resume: the headline acceptance test -------------------

def test_kill_and_resume_bit_identical():
    """Killing a party mid-round, respawning, and replaying produces
    a bit-identical aggregate, accept bitmap, and share bytes to the
    fault-free run (MasticCount, CPU; the joint-rand instance runs in
    the slow tier)."""
    m = MasticCount(2)
    vk = gen_rand(m.VERIFY_KEY_SIZE)
    reports = _count_reports(m, [(False, True), (True, False),
                                 (False, False)])

    sess0 = AggregationSession(m, COUNT_SPEC, CTX, vk,
                               config=CFG_ROUND)
    try:
        sess0.upload(reports)
        (r0, a0, s0) = sess0.round(COUNT_PARAM)
    finally:
        sess0.close()
    assert list(a0) == [True, True, True]
    assert r0 == [2, 1]

    sess1 = AggregationSession(
        m, COUNT_SPEC, CTX, vk, config=CFG_ROUND,
        faults_spec="kill:party=helper:step=round_start")
    try:
        sess1.upload(reports)
        (r1, a1, s1) = sess1.round(COUNT_PARAM)
    finally:
        sess1.close()
    assert sess1.counters["respawns"] == 1
    assert sess1.counters["retries"] >= 1
    assert r1 == r0
    assert list(a1) == list(a0)
    assert s1 == s0                      # bit-identical share bytes


# -- full-round fault matrix (each case pays a real round) -----------

@pytest.mark.slow
@pytest.mark.parametrize("spec,expect", [
    # Corrupted prep share: the flipped eval-proof byte rejects that
    # report — refusal, never acceptance of a wrong aggregate.
    ("corrupt:party=helper:step=prep_share:offset=4",
     ("completes", [False, True], [0, 1])),
    # Duplicated prep share: the round itself completes correctly
    # (the stale frame desyncs the NEXT exchange, not this one).
    ("duplicate:party=helper:step=prep_share",
     ("completes", [True, True], [1, 1])),
    # Truncated prep share: the leader waits for bytes that never
    # arrive and NAKs with a timeout attributed to the helper.
    ("truncate:party=helper:step=prep_share:cut=8",
     ("error", "helper", ("timeout", "closed"))),
    # Leader killed after prep: the collector sees the closed channel
    # and attributes the crash ("closed" only if the reap race beats
    # the grace poll).
    ("kill:party=leader:step=prep_done",
     ("error", "leader", ("crashed", "closed"))),
    # Helper hangs before prep ever runs: bounded by the deadline.
    ("hang:party=helper:step=round_start",
     ("error", "helper", ("timeout", "crashed"))),
])
def test_full_round_fault_matrix(spec, expect):
    """Every injected fault class terminates within the configured
    deadline with a structured, party-attributed outcome — and no
    fault ever yields a silently wrong aggregate."""
    m = MasticCount(2)
    reports = _count_reports(m, [(False, True), (True, False)])
    cfg = SessionConfig(connect_timeout=30.0, exchange_timeout=150.0,
                        ack_timeout=60.0, round_deadline=400.0,
                        shutdown_timeout=5.0, retries=0, backoff=0.2)
    coll = ProcessCollector(m, COUNT_SPEC, CTX,
                            gen_rand(m.VERIFY_KEY_SIZE), config=cfg,
                            faults_spec=spec)
    t0 = time.monotonic()
    try:
        coll.upload(reports)
        if expect[0] == "completes":
            (result, accept, _shares) = coll.round(COUNT_PARAM)
            assert list(accept) == expect[1]
            assert result == expect[2]
        else:
            with pytest.raises(SessionError) as ei:
                coll.round(COUNT_PARAM)
            (_, party, kinds) = expect
            assert ei.value.party == party
            assert ei.value.kind in kinds
        assert time.monotonic() - t0 < cfg.round_deadline + 120
    finally:
        coll.close()


@pytest.mark.slow
def test_kill_and_resume_joint_rand_instance():
    """The weight-check / joint-rand instantiation (histogram)
    survives a mid-round kill the same way: respawn + replay is
    bit-identical."""
    m = MasticHistogram(2, 4, 2)
    spec = {"class": "MasticHistogram", "args": [2, 4, 2]}
    vk = gen_rand(m.VERIFY_KEY_SIZE)
    param = (0, ((False,), (True,)), True)
    reports = []
    for (alpha, weight) in [((False, False), 3), ((True, False), 1)]:
        nonce = gen_rand(m.NONCE_SIZE)
        (ps, shares) = m.shard(CTX, (alpha, weight), nonce,
                               gen_rand(m.RAND_SIZE))
        reports.append((nonce, ps, shares))

    sess0 = AggregationSession(m, spec, CTX, vk, config=CFG_ROUND)
    try:
        sess0.upload(reports)
        (r0, a0, s0) = sess0.round(param)
    finally:
        sess0.close()
    assert list(a0) == [True, True]

    sess1 = AggregationSession(
        m, spec, CTX, vk, config=CFG_ROUND,
        faults_spec="kill:party=leader:step=prep_done")
    try:
        sess1.upload(reports)
        (r1, a1, s1) = sess1.round(param)
    finally:
        sess1.close()
    assert sess1.counters["respawns"] == 1
    assert (r1, list(a1), s1) == (r0, list(a0), s0)


@pytest.mark.slow
def test_snapshot_resume_replays_completed_round():
    """A collector crash after a completed round resumes from the
    snapshot: the round replays from stored state (fast, no party
    round-trip) bit-identically."""
    m = MasticCount(2)
    vk = gen_rand(m.VERIFY_KEY_SIZE)
    reports = _count_reports(m, [(False, True), (True, False)])
    sess = AggregationSession(m, COUNT_SPEC, CTX, vk,
                              config=CFG_ROUND)
    try:
        sess.upload(reports)
        (r0, a0, s0) = sess.round(COUNT_PARAM)
        blob = sess.to_bytes()
    finally:
        sess.close()

    sess2 = AggregationSession.from_bytes(blob, config=CFG_ROUND)
    try:
        t0 = time.monotonic()
        (r1, a1, s1) = sess2.round(COUNT_PARAM)
        assert time.monotonic() - t0 < 10   # replayed, not re-run
        assert (r1, list(a1), s1) == (r0, list(a0), s0)
    finally:
        sess2.close()
