"""The live status surface (ISSUE 7 tentpole, part 4): a stdlib
`http.server` thread serving

    /metrics   Prometheus text exposition (the registry)
    /statusz   human-readable service status (per-tenant occupancy,
               epoch queue depths, shed/quarantine totals, the last
               round's timeline)
    /varz      one JSON object: registry snapshot + tracer state +
               whatever dict the embedding process publishes

The scheduler (`tools/serve.py`) is single-threaded by design, so the
server NEVER calls into live service objects: the embedding process
publishes an immutable snapshot dict after each scheduler quantum
(`StatusServer.publish`, copy-on-write under a lock), and request
handlers only read the latest published snapshot plus the registry
(whose own operations are lock-protected).  A scrape can therefore
never race a round or observe a half-updated tenant table.

Port 0 binds an ephemeral port (`server.port` reports the real one) —
how the smoke gate and the tests avoid collisions.
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .registry import get_registry
from .trace import get_tracer


def render_statusz(snapshot: dict) -> str:
    """The human text page from a published service snapshot (the
    `CollectorService.metrics()` shape).  Tolerates an empty snapshot
    (server up before the first quantum)."""
    lines = ["mastic collector statusz", ""]
    if not snapshot:
        lines.append("(no snapshot published yet)")
        return "\n".join(lines) + "\n"
    lines.append(f"shed policy: {snapshot.get('policy', '?')}   "
                 f"resumed: {snapshot.get('resumed', False)}")
    for (name, t) in sorted(snapshot.get("tenants", {}).items()):
        c = t.get("counters", {})
        lines.append("")
        lines.append(f"tenant {name}"
                     + ("   [SUSPENDED]" if t.get("suspended")
                        else ""))
        lines.append(
            f"  occupancy: {t.get('buffered_reports', 0)} buffered "
            f"({t.get('open_page', 0)} open-page, "
            f"{t.get('sealed_pages', 0)} sealed pages), "
            f"{t.get('pending_epochs', 0)} pending epochs, "
            f"active={t.get('active_epoch')}")
        lines.append(
            f"  counters: admitted={c.get('admitted', 0)} "
            f"rounds={c.get('rounds', 0)} "
            f"quarantined={c.get('quarantined', 0)} "
            f"shed={c.get('shed', 0)} "
            f"deadline_misses={c.get('deadline_misses', 0)} "
            f"resumes={c.get('resumes', 0)}")
        for (table, label) in (("shed_reasons", "shed"),
                               ("quarantine_reasons", "quarantine")):
            reasons = c.get(table) or {}
            if reasons:
                body = ", ".join(f"{k}={v}" for (k, v)
                                 in sorted(reasons.items()))
                lines.append(f"  {label} reasons: {body}")
        epochs = t.get("epochs") or []
        if epochs:
            last = epochs[-1]
            lines.append(
                f"  last epoch: id={last.get('epoch')} "
                f"reports={last.get('reports')} "
                f"truncated={last.get('truncated')} "
                f"levels={last.get('levels_completed')} "
                f"wall_s={last.get('wall_s', '?')}")
        timeline = t.get("last_round_timeline")
        if timeline:
            lines.append("  last round timeline (per chunk, ms):")
            for rec in timeline:
                phases = rec.get("phases", {})
                body = " ".join(f"{k[:-3]}={v:.1f}" for (k, v)
                                in sorted(phases.items()))
                lines.append(f"    chunk {rec.get('chunk')}: "
                             f"wall={rec.get('wall_ms', 0)} {body}")
    return "\n".join(lines) + "\n"


class _Handler(BaseHTTPRequestHandler):
    server_version = "mastic-statusz/1"

    def _send(self, code: int, body: str,
              ctype: str = "text/plain; charset=utf-8") -> None:
        data = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self) -> None:  # noqa: N802 (http.server API)
        owner: "StatusServer" = self.server.owner  # type: ignore
        path = self.path.split("?", 1)[0]
        if path == "/metrics":
            self._send(200, owner.registry.prometheus_text(),
                       "text/plain; version=0.0.4; charset=utf-8")
        elif path == "/varz":
            self._send(200, json.dumps(owner.varz(), sort_keys=True),
                       "application/json")
        elif path in ("/statusz", "/"):
            self._send(200, render_statusz(owner.snapshot()))
        else:
            self._send(404, f"no route {path}\n")

    def log_message(self, fmt: str, *args) -> None:
        """Scrapes are high-frequency; stderr chatter off by
        default."""


class StatusServer:
    """The embedding process's handle: start() binds and spawns the
    daemon thread, publish() swaps in a new snapshot, stop() shuts
    the listener down (tests; the service normally lives as long as
    the process)."""

    def __init__(self, port: int = 0, registry=None, tracer=None):
        self.requested_port = port
        self.registry = (registry if registry is not None
                         else get_registry())
        self.tracer = tracer if tracer is not None else get_tracer()
        self._lock = threading.Lock()
        self._snapshot: dict = {}
        self._extra_varz: dict = {}
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.port: Optional[int] = None

    def start(self) -> "StatusServer":
        self._httpd = ThreadingHTTPServer(
            ("127.0.0.1", self.requested_port), _Handler)
        # mastic-allow: CC001 — publication handoff: `owner` is
        # written once, strictly before Thread.start() below, and
        # never reassigned; the server thread's reads are ordered
        # after the start() happens-before edge, so no lock is needed
        self._httpd.owner = self  # type: ignore[attr-defined]
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="mastic-statusz", daemon=True)
        self._thread.start()
        return self

    def publish(self, snapshot: dict,
                extra_varz: Optional[dict] = None) -> None:
        """Swap in the scheduler's latest snapshot (the dict is
        adopted, not copied — pass a fresh one each quantum)."""
        with self._lock:
            self._snapshot = snapshot
            if extra_varz is not None:
                self._extra_varz = extra_varz

    def snapshot(self) -> dict:
        # Snapshot OUT, not the guarded reference: the scheduler
        # swaps whole dicts in publish(), but handing the live object
        # across the lock boundary would let a future mutation race a
        # scrape (the r12 docstring promised copy-on-write; the CC003
        # analyzer rule now enforces the copy).
        with self._lock:
            return dict(self._snapshot)

    def varz(self) -> dict:
        with self._lock:
            extra = dict(self._extra_varz)
            snap = dict(self._snapshot)
        return {
            "metrics": self.registry.snapshot(),
            "trace": self.tracer.snapshot(),
            "service": snap,
            **extra,
        }

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
