"""Known-good: every index_map takes one index per grid axis."""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def call(kernel):
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((16, 256), jnp.uint32),
        grid=(2, 2),
        in_specs=[pl.BlockSpec((8, 128), lambda i, j: (i, j))],
        out_specs=pl.BlockSpec((8, 128), lambda i, j: (i, j)),
    )
