"""Attribute-based metrics mode: a single aggregation at the last
level with hashed attributes as the index space.

Functionally equivalent to the reference
(/root/reference/poc/examples.py:172-260; spec mode
draft-mouris-cfrg-mastic.md:1574-1611): alpha = H(attribute) truncated
to BITS, one weight-checked aggregation at level BITS-1 with the
candidate prefixes being the collector's attributes of interest.
"""

import hashlib
from typing import Optional, Sequence

from ..common import gen_rand
from ..mastic import Mastic
from ..backend.mastic_jax import BatchedMastic
from .heavy_hitters import run_round


def hash_attribute(mastic: Mastic, attribute: str) -> tuple:
    """SHA3-256 the attribute and keep the first BITS bits (the
    reference truncates the same way for BITS=8; collision resistance
    governs how small BITS may be in practice)."""
    bits = mastic.vidpf.BITS
    digest = hashlib.sha3_256(attribute.encode()).digest()
    value = int.from_bytes(digest[:(bits + 7) // 8], "big")
    value >>= (8 - bits % 8) % 8
    return mastic.vidpf.test_index_from_int(value, bits)


def aggregate_by_attribute(mastic: Mastic, ctx: bytes,
                           attributes: Sequence[str], reports: list,
                           verify_key: Optional[bytes] = None,
                           metrics_out: Optional[list] = None) -> list:
    """Aggregate `reports` grouped by the collector's attributes of
    interest.  Returns [(attribute, aggregate)] pairs; appends a
    RoundMetrics record to `metrics_out` (observability, SURVEY §5)."""
    if verify_key is None:
        verify_key = gen_rand(mastic.VERIFY_KEY_SIZE)
    bm = BatchedMastic(mastic)
    batch = bm.marshal_reports(reports)
    level = mastic.vidpf.BITS - 1
    prefixes = tuple(hash_attribute(mastic, a) for a in attributes)
    if len(set(prefixes)) != len(prefixes):
        raise ValueError("attribute hash collision; increase BITS")
    agg_param = (level, prefixes, True)
    assert mastic.is_valid(agg_param, [])
    result = run_round(bm, verify_key, ctx, agg_param, batch, reports,
                       metrics_out=metrics_out)
    return list(zip(attributes, result))
