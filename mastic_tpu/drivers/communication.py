"""Communication-cost report (the reference's overhead example,
/root/reference/poc/examples.py:263-364, rebuilt on this framework's
codecs).

Reports this framework's *measured* wire sizes by encoding real
reports for the same configs the reference benchmarks, plus the
protocol-shape facts the spec itself states (1 prep round vs
Poplar1's 2; O(num_measurements x BITS) inter-aggregator traffic,
draft-mouris-cfrg-mastic.md:166-168, :1619-1623).

The reference's headline comparison — Mastic vs Poplar1(256) upload,
MasticHistogram vs Prio3Histogram upload — is reproduced *analytically*
from the published vdaf-13 constants (the Poplar1/Prio3
implementations themselves are out of scope, SURVEY.md §2.2):
Poplar1's sizes follow from the IdpfBBCGGI21 wire structure (vdaf-13
§8), and Prio3Histogram's from the Prio3 wire layout (vdaf-13 §7) with
MEAS_LEN/PROOF_LEN taken from this framework's own vector-locked
Histogram circuit — Prio3 uses the identical BBCGGI19 circuit family.
"""

from .. import testvec_codec as codec
from ..common import gen_rand
from ..field import Field128
from ..flp.circuits import Histogram
from ..flp.flp import FlpBBCGGI19
from ..mastic import Mastic, MasticCount, MasticHistogram, MasticSum


def report_sizes(mastic: Mastic, measurement) -> dict:
    """Encode one report and measure each wire message."""
    ctx = b"sizes"
    nonce = gen_rand(mastic.NONCE_SIZE)
    rand = gen_rand(mastic.RAND_SIZE)
    (public_share, input_shares) = mastic.shard(ctx, measurement, nonce,
                                                rand)
    public = len(codec.encode_public_share(mastic, public_share))
    leader = len(codec.encode_input_share(mastic, input_shares[0]))
    helper = len(codec.encode_input_share(mastic, input_shares[1]))
    return {
        "public_share": public,
        "leader_share": leader,
        "helper_share": helper,
        "upload": public + leader + helper,
    }


def poplar1_sizes(bits: int) -> dict:
    """Analytic Poplar1(bits) upload sizes from the published vdaf-13
    §8 wire structure (the comparison target of the reference's
    example_poplar1_overhead, /root/reference/poc/examples.py:263-281).

    IdpfBBCGGI21: KEY_SIZE 16, two packed control bits per level, a
    16-byte seed correction per level, VALUE_LEN 2 payload corrections
    over Field64 (8 B) for inner levels and Field255 (32 B) for the
    leaf.  Each input share carries the IDPF key and a 32-byte
    correlated-randomness seed; the leader additionally carries the
    explicit sketch correlation — a (a, b, c) triple per level, Field64
    inner / Field255 leaf.
    """
    public = ((2 * bits + 7) // 8    # packed control bits
              + bits * 16            # seed corrections
              + (bits - 1) * 2 * 8   # inner payload corrections
              + 2 * 32)              # leaf payload correction
    leader = 16 + 32 + 3 * (bits - 1) * 8 + 3 * 32
    helper = 16 + 32
    return {
        "public_share": public,
        "leader_share": leader,
        "helper_share": helper,
        "upload": public + leader + helper,
        "analytic": True,
    }


def prio3_histogram_sizes(length: int, chunk_length: int) -> dict:
    """Analytic Prio3Histogram(2 shares, length, chunk_length) upload
    sizes from the vdaf-13 §7 wire layout, with MEAS_LEN / PROOF_LEN
    taken from this framework's vector-locked Histogram circuit (Prio3
    instantiates the identical BBCGGI19 circuit over Field128).

    Public share: one 32-byte joint-rand part per aggregator.  Leader
    share: explicit measurement + proof shares plus a 32-byte
    joint-rand blind.  Helper share: a 32-byte expansion seed plus the
    blind.
    """
    flp = FlpBBCGGI19(Histogram(Field128, length, chunk_length))
    elem = Field128.ENCODED_SIZE
    public = 2 * 32
    leader = (flp.MEAS_LEN + flp.PROOF_LEN) * elem + 32
    helper = 32 + 32
    return {
        "public_share": public,
        "leader_share": leader,
        "helper_share": helper,
        "upload": public + leader + helper,
        "analytic": True,
    }


def communication_report(print_fn=print) -> dict:
    """Mastic upload sizes for the reference's comparison configs,
    plus the analytic Poplar1/Prio3 comparison story
    (reference examples.py:263-364)."""
    out = {}
    alpha256 = (False,) * 256

    out["MasticCount(256)"] = report_sizes(MasticCount(256),
                                           (alpha256, 1))
    out["MasticSum(256, max=255)"] = report_sizes(
        MasticSum(256, 255), (alpha256, 17))
    out["MasticHistogram(32, 100, 10)"] = report_sizes(
        MasticHistogram(32, 100, 10), ((False,) * 32, 3))
    out["Poplar1(256)"] = poplar1_sizes(256)
    # The reference compares MasticHistogram(32, 100, 10) in
    # attribute-metrics mode (100 attributes x 100 buckets) against a
    # flat Prio3Histogram over 100*100 buckets with
    # chunk = floor(sqrt(10000)) (examples.py:343-346).
    out["Prio3Histogram(10000, 100)"] = prio3_histogram_sizes(10000, 100)
    out["prep_rounds"] = {"mastic": 1, "poplar1_spec": 2}
    out["mastic_count_vs_poplar1_upload"] = (
        out["MasticCount(256)"]["upload"]
        / out["Poplar1(256)"]["upload"])
    out["prio3_vs_mastic_histogram_upload"] = (
        out["Prio3Histogram(10000, 100)"]["upload"]
        / out["MasticHistogram(32, 100, 10)"]["upload"])

    for (name, sizes) in out.items():
        print_fn(f"{name}: {sizes}")
    return out
