"""Pass 2 — dtype discipline in the field/AES/Keccak kernels.

Scope: mastic_tpu/ops/ — the modules where bit-exactness is the
contract and every limb is a uint8/uint32 whose width is part of the
math.  A small dtype lattice ({uint8, uint16, uint32, bool, unknown})
is walked over each function body: dtypes enter through explicit
constructors (`jnp.uint32(x)`, `_U8(x)` aliases, `astype`, the dtype
arguments of jnp.zeros/full/arange/asarray/sum) and propagate through
assignments, slicing, `.at[...].set`, and the shape-preserving jnp
ops.  The walker is conservative: unknown never flags.

Rules:
  DT001  binary op mixing two *known, different* unsigned widths
         (uint8 with uint32) without an explicit astype — jnp promotes
         silently and the narrow side's overflow semantics are lost.
  DT002  `.astype(uint8)` over an expression containing a widening op
         (`<<` or `*`) that is not already masked down to the target
         range (`& 0xFF`-style): the astype silently truncates bits
         the widening op produced.  Where the truncation IS the math
         (AES xtime), suppress with the justification.
  DT003  bare int literal mixed with a known-dtype array when the
         literal does not fit the dtype (e.g. `u8 & 0x1FF`), or a
         shift count >= the dtype's bit width — both are silent
         all-zeros/garbage on device.
"""

import ast

from .core import Finding, call_name, root_name

PASS_NAME = "dtypes"

RULES = {
    "DT001": "implicit promotion between different unsigned widths",
    "DT002": "narrowing astype over an unmasked widening op",
    "DT003": "int literal / shift count out of range for the dtype",
}

SCOPE_PREFIXES = ("mastic_tpu/ops/",)

_DTYPE_ATTRS = {"uint8": "u8", "uint16": "u16", "uint32": "u32",
                "int32": "i32", "int64": "i64", "bool_": "bool"}
_MAX = {"u8": 0xFF, "u16": 0xFFFF, "u32": 0xFFFFFFFF}
_BITS = {"u8": 8, "u16": 16, "u32": 32}
_UNSIGNED = {"u8", "u16", "u32"}
# jnp calls that preserve the dtype of their first array argument.
_PRESERVE = {"reshape", "concatenate", "stack", "moveaxis", "roll",
             "broadcast_to", "pad", "where", "transpose", "squeeze",
             "expand_dims", "flip", "swapaxes", "zeros_like",
             "ones_like", "tile", "repeat"}
# array methods that preserve the receiver's dtype.
_PRESERVE_METHODS = {"reshape", "set", "add", "get", "min", "max",
                     "multiply", "transpose"}
_DTYPE_ARG_FNS = {"zeros", "ones", "full", "empty", "asarray",
                  "arange", "array", "sum", "iota", "broadcasted_iota"}


def in_scope(rel: str) -> bool:
    return rel.startswith(SCOPE_PREFIXES)


def _dtype_aliases(tree: ast.Module) -> dict:
    """Module-level `_U32 = jnp.uint32` style aliases -> lattice tag."""
    aliases = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Attribute) \
                and node.value.attr in _DTYPE_ATTRS \
                and root_name(node.value) in ("jnp", "np", "numpy"):
            aliases[node.targets[0].id] = _DTYPE_ATTRS[node.value.attr]
    return aliases


class _DtypeWalker:
    def __init__(self, fn, info, aliases, findings):
        self.fn = fn
        self.info = info
        self.aliases = aliases
        self.findings = findings
        self.env: dict = {}

    # -- dtype of an expression ------------------------------------

    def dtype_ref(self, node):
        """`node` used as a dtype *reference* (jnp.uint8, _U8, bool)."""
        if isinstance(node, ast.Attribute) and node.attr in _DTYPE_ATTRS \
                and root_name(node) in ("jnp", "np", "numpy"):
            return _DTYPE_ATTRS[node.attr]
        if isinstance(node, ast.Name):
            if node.id in self.aliases:
                return self.aliases[node.id]
            if node.id == "bool":
                return "bool"
        return None

    def dtype_of(self, node):
        if node is None:
            return None
        if isinstance(node, ast.Name):
            return self.env.get(node.id)
        if isinstance(node, ast.Subscript):
            return self.dtype_of(node.value)
        if isinstance(node, ast.Attribute):
            if node.attr in ("at", "T"):
                return self.dtype_of(node.value)
            return None
        if isinstance(node, ast.Call):
            return self._dtype_of_call(node)
        if isinstance(node, ast.BinOp):
            left = self.dtype_of(node.left)
            right = self.dtype_of(node.right)
            if isinstance(node.op, (ast.LShift, ast.RShift)):
                return left      # shifts keep the left operand's dtype
            if left is not None and right is None:
                return left
            if right is not None and left is None:
                return right
            if left == right:
                return left
            return None          # mixed: DT001's business, not ours
        if isinstance(node, ast.UnaryOp):
            return self.dtype_of(node.operand)
        if isinstance(node, ast.IfExp):
            return self.dtype_of(node.body) or self.dtype_of(node.orelse)
        return None

    def _dtype_of_call(self, node: ast.Call):
        ctor = self.dtype_ref(node.func)
        if ctor is not None:
            return ctor          # _U32(x), jnp.uint8(x)
        if isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            if attr == "astype" and node.args:
                return self.dtype_ref(node.args[0])
            name = call_name(node)
            root = root_name(node.func)
            if root in ("jnp", "np", "numpy", "lax", "jax"):
                if attr in _DTYPE_ARG_FNS:
                    for kw in node.keywords:
                        if kw.arg == "dtype":
                            return self.dtype_ref(kw.value)
                    if attr in ("zeros", "ones", "full", "empty"):
                        if len(node.args) >= 2 + (attr == "full"):
                            return self.dtype_ref(node.args[-1])
                    if attr in ("asarray", "array") \
                            and len(node.args) >= 2:
                        return self.dtype_ref(node.args[1])
                    if attr in ("iota", "broadcasted_iota") \
                            and node.args:
                        return self.dtype_ref(node.args[0])
                    return None
                if attr in _PRESERVE:
                    for a in node.args:
                        if attr == "where" and a is node.args[0]:
                            continue   # dtype comes from the branches
                        d = self.dtype_of(a)
                        if d is not None:
                            return d
                    return None
                if attr in ("zeros_like", "ones_like") and node.args:
                    return self.dtype_of(node.args[0])
            if attr in _PRESERVE_METHODS:
                return self.dtype_of(node.func.value)
        return None

    # -- propagation + checks --------------------------------------

    def run(self):
        from .tracesafe import iter_scope

        for _ in range(10):
            before = dict(self.env)
            for node in iter_scope(self.fn):
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    d = self.dtype_of(node.value)
                    if d is not None:
                        self.env[node.targets[0].id] = d
            if self.env == before:
                break
        for node in iter_scope(self.fn):
            if isinstance(node, ast.BinOp):
                self._check_binop(node)
            elif isinstance(node, ast.Call):
                self._check_astype(node)

    def _flag(self, rule, node, msg):
        self.findings.append(
            Finding(rule, self.info.rel, node.lineno, msg))

    def _literal(self, node):
        return self.info.fold(node)

    def _check_binop(self, node: ast.BinOp):
        left = self.dtype_of(node.left)
        right = self.dtype_of(node.right)
        # DT003: literal operand out of range for the known side.
        for (known, other) in ((left, node.right), (right, node.left)):
            if known not in _UNSIGNED:
                continue
            lit = self._literal(other)
            if lit is None:
                continue
            if isinstance(node.op, (ast.LShift, ast.RShift)) \
                    and other is node.right:
                if lit >= _BITS[known]:
                    self._flag("DT003", node,
                               f"shift by {lit} on a {known} value "
                               f"(width {_BITS[known]}) is all-zeros")
            elif lit > _MAX[known] or lit < 0:
                self._flag("DT003", node,
                           f"literal {hex(lit) if lit >= 0 else lit} "
                           f"does not fit {known} "
                           f"(max {hex(_MAX[known])})")
            return
        # DT001: two known, different unsigned widths.
        if left in _UNSIGNED and right in _UNSIGNED and left != right \
                and not isinstance(node.op, (ast.LShift, ast.RShift)):
            self._flag("DT001", node,
                       f"binary op mixes {left} and {right} — promote "
                       "explicitly with astype")

    def _check_astype(self, node: ast.Call):
        if not (isinstance(node.func, ast.Attribute)
                and node.func.attr == "astype" and node.args):
            return
        target = self.dtype_ref(node.args[0])
        if target != "u8":
            return
        hit = _find_unmasked_widening(node.func.value, _MAX[target])
        if hit is not None:
            self._flag("DT002", node,
                       "astype(uint8) truncates an expression with an "
                       f"unmasked widening op ('{ast.unparse(hit)[:48]}'"
                       ") — mask with & 0xFF first or suppress with "
                       "the justification")


def _find_unmasked_widening(node, target_max):
    """First `<<` or `*` BinOp inside `node` not already below a
    masking `& <literal <= target_max>` or an inner astype."""
    if isinstance(node, ast.BinOp):
        if isinstance(node.op, ast.BitAnd):
            for side in (node.left, node.right):
                lit = _mask_literal(side)
                if lit is not None and 0 <= lit <= target_max:
                    return None   # the mask bounds the whole subtree
        if isinstance(node.op, (ast.LShift, ast.Mult)):
            return node
        return (_find_unmasked_widening(node.left, target_max)
                or _find_unmasked_widening(node.right, target_max))
    if isinstance(node, ast.Call):
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "astype":
            return None          # inner conversion resets the range
        hits = [_find_unmasked_widening(a, target_max)
                for a in node.args]
        return next((h for h in hits if h is not None), None)
    if isinstance(node, (ast.Subscript, ast.Attribute)):
        return None              # reads of stored values, not widening
    if isinstance(node, ast.UnaryOp):
        return _find_unmasked_widening(node.operand, target_max)
    if isinstance(node, (ast.Tuple, ast.List)):
        hits = [_find_unmasked_widening(e, target_max)
                for e in node.elts]
        return next((h for h in hits if h is not None), None)
    return None


def _mask_literal(node):
    """Literal int of a masking operand: 0xFF, _U8(0xFF), uint8(255)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return node.value
    if isinstance(node, ast.Call) and len(node.args) == 1 \
            and isinstance(node.args[0], ast.Constant) \
            and isinstance(node.args[0].value, int):
        return node.args[0].value
    return None


def check(info) -> list:
    aliases = _dtype_aliases(info.tree)
    findings: list = []

    def visit(body):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _DtypeWalker(node, info, aliases, findings).run()
                visit(node.body)
            elif isinstance(node, ast.ClassDef):
                visit(node.body)

    visit(info.tree.body)
    seen = set()
    out = []
    for f in findings:
        if f.key() in seen:
            continue
        seen.add(f.key())
        out.append(f)
    return out
