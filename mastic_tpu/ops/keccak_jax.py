"""Batched Keccak-p[1600] and TurboSHAKE128 in JAX.

Bit-exact against the scalar reference (mastic_tpu.keccak) — the same
round constants and rho offsets are imported from there.  Lanes are
represented as pairs of uint32 arrays (lo, hi) with a trailing lane
axis of size 25, because TPUs have no native 64-bit integer lane type;
all 64-bit rotations decompose into static 32-bit shift pairs.

The sponge here is *shape-static*: message length, domain byte and
output length are Python ints, so the pad10*1 padding, the number of
absorb permutations and the number of squeeze permutations are all
fixed at trace time.  Data-dependent message lengths never occur in
Mastic — every XOF call site has a length determined by (public)
protocol parameters (reference poc/vidpf.py:366-380, poc/mastic.py:
452-510).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np

from ..keccak import RHO_OFFSETS, ROUND_CONSTANTS

RATE = 168  # TurboSHAKE128 rate in bytes (21 lanes)
_U32 = jnp.uint32

# Round-loop unroll factor for the permutation scan (see keccak_p1600).
# Read once at import.  The default 1 keeps compiles cheap and was the
# best rate observed in the r5 chip lever matrix (42.2M evals/s vs
# 37.5M warm at unroll=4 and 36.7M at 8 on the 4096x64x256-bit
# headline shape — single warm measurements per cell; nothing showed
# manual round fusion helping).  bench.py --keccak-unroll overrides.
UNROLL = int(os.environ.get("MASTIC_KECCAK_UNROLL", "1"))

# Route the permutation through the Pallas fused-VMEM kernel
# (ops/keccak_pallas.py) instead of the scan.  Read once at import;
# interpret mode is selected per call from the active backend so the
# CPU test fabric can exercise the kernel path bit-exactly.
USE_PALLAS = os.environ.get("MASTIC_KECCAK_PALLAS", "0") == "1"


def _rotl64(lo: jax.Array, hi: jax.Array, n: int):
    """Rotate the 64-bit lanes (hi||lo) left by static n."""
    n %= 64
    if n == 0:
        return (lo, hi)
    if n == 32:
        return (hi, lo)
    if n > 32:
        (lo, hi) = (hi, lo)
        n -= 32
    m = 32 - n
    new_lo = (lo << n) | (hi >> m)
    new_hi = (hi << n) | (lo >> m)
    return (new_lo, new_hi)


def _keccak_round(a: list, rc_lo: jax.Array, rc_hi: jax.Array) -> list:
    """One Keccak-p round on a list of 25 (lo, hi) lane-half pairs.

    The state stays a flat list of batch-dense arrays end to end: a
    (..., 25) layout would make every lane access a stride-25 slice
    and every round a re-interleave, which XLA lowers to relayout
    copies that dominate the permutation cost on TPU (measured ~4x)."""
    # theta
    c = []
    for x in range(5):
        clo = a[x][0] ^ a[x + 5][0] ^ a[x + 10][0] \
            ^ a[x + 15][0] ^ a[x + 20][0]
        chi_ = a[x][1] ^ a[x + 5][1] ^ a[x + 10][1] \
            ^ a[x + 15][1] ^ a[x + 20][1]
        c.append((clo, chi_))
    d = []
    for x in range(5):
        (rlo, rhi) = _rotl64(*c[(x + 1) % 5], 1)
        d.append((c[(x - 1) % 5][0] ^ rlo, c[(x - 1) % 5][1] ^ rhi))
    a = [(a[x + 5 * y][0] ^ d[x][0], a[x + 5 * y][1] ^ d[x][1])
         for y in range(5) for x in range(5)]
    # rho + pi
    b = [a[0]] * 25
    for x in range(5):
        for y in range(5):
            b[y + 5 * ((2 * x + 3 * y) % 5)] = \
                _rotl64(*a[x + 5 * y], RHO_OFFSETS[x][y])
    # chi
    a = [
        (b[x + 5 * y][0] ^ (~b[(x + 1) % 5 + 5 * y][0]
                            & b[(x + 2) % 5 + 5 * y][0]),
         b[x + 5 * y][1] ^ (~b[(x + 1) % 5 + 5 * y][1]
                            & b[(x + 2) % 5 + 5 * y][1]))
        for y in range(5) for x in range(5)
    ]
    # iota
    a[0] = (a[0][0] ^ rc_lo, a[0][1] ^ rc_hi)
    return a


# Kept as numpy at module scope so importing this module never
# initializes the JAX backend (callers may still need to override the
# platform); jnp.asarray at use site is constant-folded by XLA.
_RC_LO = np.asarray([rc & 0xFFFFFFFF for rc in ROUND_CONSTANTS],
                    np.uint32)
_RC_HI = np.asarray([rc >> 32 for rc in ROUND_CONSTANTS], np.uint32)


def keccak_p1600(lo: jax.Array, hi: jax.Array, num_rounds: int = 12):
    """Apply Keccak-p[1600, num_rounds] to batched lanes.

    `lo`/`hi` have shape (..., 25), lane order A[x + 5*y] as in the
    scalar reference (mastic_tpu.keccak.keccak_p1600).  Rounds run
    under lax.scan so the round body compiles once — the permutation
    is called at every tree node and the unrolled form dominated XLA
    compile time.
    """

    if USE_PALLAS:
        from .keccak_pallas import keccak_p1600_pallas

        # mastic-allow: TS004 — deliberate trace-time constant:
        # interpret mode must be baked per backend, and jax retraces
        # per backend, so the frozen value can never go stale
        return keccak_p1600_pallas(
            lo, hi, num_rounds,
            interpret=jax.default_backend() == "cpu")

    def body(carry, rcs):
        (rc_lo, rc_hi) = rcs
        a = [(carry[i], carry[25 + i]) for i in range(25)]
        a = _keccak_round(a, rc_lo, rc_hi)
        return ([x[0] for x in a] + [x[1] for x in a], None)

    start = 24 - num_rounds
    # De-interleave once at entry, re-interleave once at exit: the
    # scan carry is a flat list of 50 batch-dense uint32 arrays.
    # UNROLL trades compile time for fusion across rounds (the scan
    # carry otherwise round-trips 50 arrays through HBM every round).
    lanes = [lo[..., i] for i in range(25)] + \
        [hi[..., i] for i in range(25)]
    (lanes, _) = jax.lax.scan(
        body, lanes,
        (jnp.asarray(_RC_LO[start:]), jnp.asarray(_RC_HI[start:])),
        unroll=UNROLL)
    return (jnp.stack(lanes[:25], axis=-1),
            jnp.stack(lanes[25:], axis=-1))


def bytes_to_lanes(data: jax.Array):
    """uint8 (..., 8*n) -> little-endian uint32 lane halves
    (lo, hi) of shape (..., n)."""
    assert data.shape[-1] % 8 == 0
    words = data.reshape(data.shape[:-1] + (-1, 2, 4)).astype(_U32)
    shifts = _U32(1) << jnp.arange(0, 32, 8, dtype=_U32)
    packed = jnp.sum(words * shifts, axis=-1, dtype=_U32)
    return (packed[..., 0], packed[..., 1])


def lanes_to_bytes(lo: jax.Array, hi: jax.Array) -> jax.Array:
    """Inverse of bytes_to_lanes: (..., n) halves -> uint8 (..., 8*n)."""
    packed = jnp.stack([lo, hi], axis=-1)
    shifts = jnp.arange(0, 32, 8, dtype=_U32)
    by = (packed[..., None] >> shifts) & _U32(0xFF)
    return by.reshape(by.shape[:-3] + (-1,)).astype(jnp.uint8)


def turbo_shake128_dynamic(msg: jax.Array, length: jax.Array,
                           domain: int, out_len: int,
                           num_rounds: int = 12) -> jax.Array:
    """TurboSHAKE128 over a runtime-length prefix of `msg`.

    msg: uint8 (..., max_len) — bytes at positions >= `length` are
    ignored (masked to zero before padding).  `length` is a traced
    int32 scalar shared by the whole batch (in Mastic every
    runtime-varying message length is public protocol data, identical
    across reports).  Byte-exact vs turbo_shake128(msg[..., :length])
    for every length in [0, max_len].

    The absorb loop is a lax.while_loop over blocks, so the compiled
    program serves any length up to max_len and the runtime cost
    scales with the actual number of blocks, not the capacity.
    """
    assert 0x01 <= domain <= 0x7F
    length = jnp.asarray(length, jnp.int32)
    max_len = msg.shape[-1]
    batch_shape = msg.shape[:-1]
    max_blocks = max_len // RATE + 1
    total = max_blocks * RATE

    buf = jnp.zeros(batch_shape + (total,), jnp.uint8)
    buf = buf.at[..., :max_len].set(msg)
    pos = jnp.arange(total, dtype=jnp.int32)
    # pad10*1: zero the tail, fold the domain byte in at `length`, set
    # the top bit of the final byte of the last (padded) block.
    buf = jnp.where(pos < length, buf, 0)
    buf = buf ^ jnp.where(pos == length, jnp.uint8(domain),
                          jnp.uint8(0))
    num_blocks = length // RATE + 1
    buf = buf ^ jnp.where(pos == num_blocks * RATE - 1, jnp.uint8(0x80),
                          jnp.uint8(0))

    blocks = buf.reshape(batch_shape + (max_blocks, RATE))
    (mlo, mhi) = bytes_to_lanes(blocks)  # (..., max_blocks, 21)

    def cond(carry):
        (i, _lo, _hi) = carry
        return i < num_blocks

    def step(carry):
        (i, lo, hi) = carry
        blo = jnp.take_along_axis(
            mlo, jnp.full(batch_shape + (1, 1), i), axis=-2)[..., 0, :]
        bhi = jnp.take_along_axis(
            mhi, jnp.full(batch_shape + (1, 1), i), axis=-2)[..., 0, :]
        lo = lo.at[..., :21].set(lo[..., :21] ^ blo)
        hi = hi.at[..., :21].set(hi[..., :21] ^ bhi)
        (lo, hi) = keccak_p1600(lo, hi, num_rounds)
        return (i + 1, lo, hi)

    lo = jnp.zeros(batch_shape + (25,), _U32)
    hi = jnp.zeros(batch_shape + (25,), _U32)
    (_, lo, hi) = jax.lax.while_loop(
        cond, step, (jnp.int32(0), lo, hi))

    if out_len == 0:
        return jnp.zeros(batch_shape + (0,), jnp.uint8)
    out = []
    produced = 0
    while produced < out_len:
        if produced > 0:
            (lo, hi) = keccak_p1600(lo, hi, num_rounds)
        out.append(lanes_to_bytes(lo[..., :21], hi[..., :21]))
        produced += RATE
    full = jnp.concatenate(out, axis=-1) if len(out) > 1 else out[0]
    return full[..., :out_len]


def _pad_message(msg: jax.Array, domain: int) -> jax.Array:
    """pad10*1 with the domain byte folded in (scalar reference:
    Sponge.finalize, mastic_tpu/keccak.py:126-134)."""
    length = msg.shape[-1]
    num_blocks = length // RATE + 1
    padded = jnp.zeros(msg.shape[:-1] + (num_blocks * RATE,), jnp.uint8)
    padded = padded.at[..., :length].set(msg)
    padded = padded.at[..., length].set(padded[..., length] ^ domain)
    return padded.at[..., -1].set(padded[..., -1] ^ 0x80)


def turbo_shake128(msg: jax.Array, domain: int, out_len: int,
                   num_rounds: int = 12) -> jax.Array:
    """Batched TurboSHAKE128(M, D, L) over uint8 messages of static
    length: msg (..., L) -> (..., out_len)."""
    assert 0x01 <= domain <= 0x7F
    padded = _pad_message(msg, domain)
    batch_shape = padded.shape[:-1]
    num_blocks = padded.shape[-1] // RATE
    blocks = padded.reshape(batch_shape + (num_blocks, RATE))
    # Lane-ify: each 168-byte block is 21 lanes.
    (mlo, mhi) = bytes_to_lanes(blocks)  # (..., num_blocks, 21)

    lo = jnp.zeros(batch_shape + (25,), _U32)
    hi = jnp.zeros(batch_shape + (25,), _U32)

    if num_blocks <= 4:
        for i in range(num_blocks):
            lo = lo.at[..., :21].set(lo[..., :21] ^ mlo[..., i, :])
            hi = hi.at[..., :21].set(hi[..., :21] ^ mhi[..., i, :])
            (lo, hi) = keccak_p1600(lo, hi, num_rounds)
    else:
        # Long absorbs (e.g. the Mastic check binders over thousands of
        # nodes) scan over blocks to keep the compiled program small.
        def step(carry, xs):
            (lo, hi) = carry
            (blo, bhi) = xs
            lo = lo.at[..., :21].set(lo[..., :21] ^ blo)
            hi = hi.at[..., :21].set(hi[..., :21] ^ bhi)
            return (keccak_p1600(lo, hi, num_rounds), None)

        (blo, bhi) = (jnp.moveaxis(mlo, -2, 0), jnp.moveaxis(mhi, -2, 0))
        ((lo, hi), _) = jax.lax.scan(step, (lo, hi), (blo, bhi))

    if out_len == 0:
        return jnp.zeros(batch_shape + (0,), jnp.uint8)
    out = []
    produced = 0
    while produced < out_len:
        if produced > 0:
            (lo, hi) = keccak_p1600(lo, hi, num_rounds)
        out.append(lanes_to_bytes(lo[..., :21], hi[..., :21]))
        produced += RATE
    full = jnp.concatenate(out, axis=-1) if len(out) > 1 else out[0]
    return full[..., :out_len]
