"""Pass 4 — Pallas call/BlockSpec consistency.

Scope: any analyzed file whose AST contains a `pallas_call` call
(today: ops/level_pallas.py, ops/aes_pallas.py, ops/keccak_pallas.py).

These are the executable subset of the Mosaic shape rules the r4/r5
chip sessions paid for in failed compiles — checked statically so a
mismatch fails `make analyze` instead of a tunnel window:

  PL001  BlockSpec whose index_map returns a tuple of different length
         than its block shape (rank mismatch: every block dim needs an
         index coordinate).
  PL002  BlockSpec index_map arity != len(grid) for pallas_calls whose
         grid is a static tuple (the index_map is called with one
         argument per grid axis).
  PL003  out_shape / out_specs element-count mismatch when both are
         literal tuples/lists in the same pallas_call.
  PL004  literal (constant-foldable) block-shape sublane dim — the
         second-to-last — that is neither 1 nor a multiple of 8:
         Mosaic only accepts such a tile when it equals the full array
         dim, which this analyzer cannot prove; suppress with the
         justification naming the array dim it equals.

Symbolic shapes (names the folder cannot resolve) are skipped — the
pass is deliberately zero-false-positive on arithmetic it cannot see.
"""

import ast

from .core import Finding, call_name

PASS_NAME = "pallasck"

RULES = {
    "PL001": "BlockSpec rank mismatch (shape vs index_map return)",
    "PL002": "BlockSpec index_map arity != grid rank",
    "PL003": "out_shape / out_specs count mismatch",
    "PL004": "literal sublane block dim neither 1 nor a multiple of 8",
}


def in_scope(rel: str, tree: ast.Module = None) -> bool:
    if tree is None:
        return False
    return any(isinstance(n, ast.Call)
               and call_name(n).endswith("pallas_call")
               for n in ast.walk(tree))


def _is_blockspec(node) -> bool:
    return (isinstance(node, ast.Call)
            and call_name(node).endswith("BlockSpec"))


def _lambda_return_len(node):
    if isinstance(node, ast.Lambda) and isinstance(node.body, ast.Tuple):
        return len(node.body.elts)
    return None


def _lambda_arity(node):
    if isinstance(node, ast.Lambda):
        a = node.args
        return len(a.posonlyargs) + len(a.args)
    return None


def _kwarg(call: ast.Call, name: str):
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _seq_len(node):
    if isinstance(node, (ast.Tuple, ast.List)):
        return len(node.elts)
    return None


def _local_consts(fn, info) -> dict:
    """Names assigned exactly once in `fn` to a foldable int."""
    counts: dict = {}
    for node in ast.walk(fn):
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.For)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        counts[n.id] = counts.get(n.id, 0) + 1
    env: dict = {}
    changed = True
    while changed:
        changed = False
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and counts.get(node.targets[0].id) == 1 \
                    and node.targets[0].id not in env:
                val = info.fold(node.value, env)
                if val is not None:
                    env[node.targets[0].id] = val
                    changed = True
    return env


def _check_blockspec(spec, info, env, grid_len, findings):
    if not spec.args:
        return
    shape = spec.args[0]
    index_map = spec.args[1] if len(spec.args) > 1 else None
    shape_len = _seq_len(shape)
    ret_len = _lambda_return_len(index_map)
    if shape_len is not None and ret_len is not None \
            and shape_len != ret_len:
        findings.append(Finding(
            "PL001", info.rel, spec.lineno,
            f"BlockSpec block shape has {shape_len} dims but its "
            f"index_map returns {ret_len} coordinates"))
    arity = _lambda_arity(index_map)
    if grid_len is not None and arity is not None and arity != grid_len:
        findings.append(Finding(
            "PL002", info.rel, spec.lineno,
            f"index_map takes {arity} grid indices but the grid has "
            f"{grid_len} axes"))
    if shape_len is not None and shape_len >= 2:
        sub = info.fold(shape.elts[-2], env)
        if sub is not None and sub != 1 and sub % 8 != 0:
            findings.append(Finding(
                "PL004", info.rel, spec.lineno,
                f"sublane block dim {sub} is neither 1 nor a multiple "
                "of 8 — Mosaic accepts it only when it equals the "
                "full array dim (suppress with that justification)"))


def check(info) -> list:
    findings: list = []
    # Map every BlockSpec to its enclosing function (for local-constant
    # folding) and, where visible, its pallas_call's static grid rank.
    fn_of: dict = {}

    def map_fns(node, fn):
        for child in ast.iter_child_nodes(node):
            child_fn = child if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef)) else fn
            fn_of[child] = child_fn
            map_fns(child, child_fn)

    map_fns(info.tree, None)
    env_cache: dict = {}

    def env_for(node):
        fn = fn_of.get(node)
        if fn is None:
            return {}
        if id(fn) not in env_cache:
            env_cache[id(fn)] = _local_consts(fn, info)
        return env_cache[id(fn)]

    grid_of_spec: dict = {}
    for node in ast.walk(info.tree):
        if not (isinstance(node, ast.Call)
                and call_name(node).endswith("pallas_call")):
            continue
        grid = _kwarg(node, "grid")
        grid_len = _seq_len(grid)
        out_shape = _kwarg(node, "out_shape")
        out_specs = _kwarg(node, "out_specs")
        n_shape = _seq_len(out_shape)
        n_specs = _seq_len(out_specs)
        if n_shape is not None and n_specs is not None \
                and n_shape != n_specs:
            findings.append(Finding(
                "PL003", info.rel, node.lineno,
                f"out_shape has {n_shape} entries but out_specs has "
                f"{n_specs}"))
        if grid_len is not None:
            for kw in ("in_specs", "out_specs"):
                seq = _kwarg(node, kw)
                elts = (seq.elts if isinstance(seq, (ast.Tuple, ast.List))
                        else [seq] if _is_blockspec(seq) else [])
                for spec in elts:
                    if _is_blockspec(spec):
                        grid_of_spec[id(spec)] = grid_len

    for node in ast.walk(info.tree):
        if _is_blockspec(node):
            _check_blockspec(node, info, env_for(node),
                             grid_of_spec.get(id(node)), findings)
    seen = set()
    out = []
    for f in findings:
        if f.key() in seen:
            continue
        seen.add(f.key())
        out.append(f)
    return out
