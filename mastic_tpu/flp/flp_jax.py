"""Batched BBCGGI19 FLP: device-side prove / query / decide.

Device twin of the scalar FlpBBCGGI19 (flp/flp.py, semantics from the
reference's use of vdaf_poc.flp_bbcggi19 at /root/reference/poc/
mastic.py:125, :250-256, :349), exact over whole report batches.

The batched design exploits three structural facts of the five Mastic
circuits (flp/circuits.py):

* every circuit has exactly ONE gadget, of degree 2 — so the gadget
  polynomial always has 2p-1 coefficients for wire domain size
  p = next_pow2(calls+1), and its evaluations on the call domain
  {alpha^k} are even-indexed entries of one size-2p NTT;
* wire values at the call points are affine-bilinear in the
  measurement share and joint-rand powers — buildable with one gather
  plus one elementwise multiply, no per-call loop;
* the random spot-check point t is per-report, so wire polynomials are
  interpolated with a batched size-p inverse NTT and Horner-evaluated
  at t (no per-report field inversions anywhere).

All arithmetic runs in the Montgomery limb domain (ops/field_jax.py);
plain limbs cross the call boundary, matching the rest of the batched
backend.  The scalar layer remains the byte-exact arbiter: every path
here is differentially tested against it (tests/test_flp_jax.py).
"""

from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..common import next_power_of_2
from ..ops.field_jax import FieldSpec, field_sum, spec_for
from ..ops.ntt_jax import ntt_plan, poly_eval_mont, pow_static, power_chain
from .circuits import Count, Histogram, MultihotCountVec, Sum, SumVec
from .flp import FlpBBCGGI19, ParallelSum


class BatchedFlp:
    """Batched prove/query/decide for one FLP instantiation."""

    def __init__(self, flp: FlpBBCGGI19):
        self.flp = flp
        self.spec: FieldSpec = spec_for(flp.field)
        valid = flp.valid
        assert len(valid.GADGETS) == 1, "Mastic circuits use one gadget"
        gadget = valid.GADGETS[0]
        self.calls = valid.GADGET_CALLS[0]
        self.arity = gadget.ARITY
        self.p = next_power_of_2(self.calls + 1)
        assert gadget.DEGREE == 2, "all five circuits are degree-2"
        self.coeff_len = 2 * (self.p - 1) + 1
        self.meas_len = valid.MEAS_LEN
        self.eval_output_len = valid.EVAL_OUTPUT_LEN

        if isinstance(valid, Count):
            self.kind = "count"
            self.gadget_kind = "mul"
            extra = []
        elif isinstance(valid, Sum):
            self.kind = "sum"
            self.gadget_kind = "polyeval"
            # range_check = offset*shares_inv + decode(meas[:b])
            #             - decode(meas[b:])    (circuits.py Sum.eval)
            bits = valid.bits
            lin = [1 << i for i in range(bits)] + \
                [-(1 << i) for i in range(bits)]
            extra = [(lin, valid.offset.int())]
        else:
            self.kind = "chunked"
            self.gadget_kind = "parallel_mul"
            assert isinstance(gadget, ParallelSum)
            self.chunk_length = gadget.count
            if isinstance(valid, SumVec):
                extra = []
            elif isinstance(valid, Histogram):
                extra = [([1] * self.meas_len, -1)]
            elif isinstance(valid, MultihotCountVec):
                lin = [1] * valid.length + \
                    [-(1 << i) for i in range(valid.bits_for_weight)]
                extra = [(lin, valid.offset.int())]
            else:
                raise ValueError(f"unsupported circuit {type(valid)}")
        # Extra (non-gadget) output rows: coefficients over meas plus a
        # constant that scales with shares_inv.
        self.extra_lin = np.array([row for (row, _) in extra],
                                  np.int64).reshape(len(extra),
                                                    self.meas_len)
        self.extra_const = [c for (_, c) in extra]

        # NTT plans (host-precomputed twiddles; compiled shapes).
        self.intt_p = ntt_plan(self.spec, self.p, inverse=True)
        self.ntt_2p = ntt_plan(self.spec, 2 * self.p, inverse=False)
        self.intt_2p = ntt_plan(self.spec, 2 * self.p, inverse=True)

        if self.kind == "chunked":
            # meas gather map: chunk k position j -> meas[k*c+j] or the
            # zero sentinel (index meas_len).
            c = self.chunk_length
            idx = np.full((self.calls, c), self.meas_len, np.int32)
            for k in range(self.calls):
                for j in range(c):
                    if k * c + j < self.meas_len:
                        idx[k, j] = k * c + j
            self.chunk_idx = idx

    # -- host-side Montgomery constants ----------------------------

    def _mont_const(self, value: int) -> np.ndarray:
        return self.spec.to_mont_host(value % self.spec.modulus)

    def _shares_inv(self, num_shares: int) -> int:
        return pow(num_shares, self.spec.modulus - 2, self.spec.modulus)

    # -- wire values at the call points ----------------------------

    def _wires(self, meas: jax.Array, joint_rand: Optional[jax.Array],
               num_shares: int) -> jax.Array:
        """Wire values for calls 1..C as (..., arity, p, n) Montgomery
        limbs with slots 0 and C+1.. zero (the caller installs the wire
        seeds at slot 0)."""
        spec = self.spec
        batch = meas.shape[:-2]
        n = spec.num_limbs
        wires = jnp.zeros(batch + (self.arity, self.p, n), jnp.uint32)
        if self.kind == "count":
            wires = wires.at[..., 0, 1, :].set(meas[..., 0, :])
            wires = wires.at[..., 1, 1, :].set(meas[..., 0, :])
            return wires
        if self.kind == "sum":
            wires = wires.at[..., 0, 1:self.calls + 1, :].set(meas)
            return wires
        # chunked: wire 2j at call k+1 = r_k^(j+1) * meas[k*c+j],
        #          wire 2j+1            = meas[k*c+j] - shares_inv
        assert joint_rand is not None
        c = self.chunk_length
        zero = jnp.zeros(batch + (1, n), jnp.uint32)
        meas_ext = jnp.concatenate([meas, zero], axis=-2)
        gathered = meas_ext[..., self.chunk_idx, :]   # (..., C, c, n)
        r_pow = power_chain(spec, joint_rand, c)       # (..., C, c, n)
        # power_chain stacks powers on axis -2 per element of the C
        # axis: joint_rand (..., C, n) -> (..., C, c, n) wanted; it
        # returns (..., c, n) stacked over -2 when given (..., n), so
        # feed it the C axis as batch.
        even = spec.mul(r_pow, gathered)
        shares_inv = jnp.asarray(
            self._mont_const(self._shares_inv(num_shares)))
        odd = spec.sub(gathered, jnp.broadcast_to(shares_inv,
                                                  gathered.shape))
        pair = jnp.stack([even, odd], axis=-2)         # (..., C, c, 2, n)
        vals = jnp.moveaxis(pair, -4, -2)              # (..., c, 2, C, n)
        vals = vals.reshape(batch + (self.arity, self.calls, n))
        return wires.at[..., 1:self.calls + 1, :].set(vals)

    # -- circuit outputs -------------------------------------------

    def _extra_outputs(self, meas: jax.Array,
                       num_shares: int) -> Optional[jax.Array]:
        """The non-gadget output rows: (..., num_extra, n) Montgomery."""
        if not len(self.extra_const):
            return None
        spec = self.spec
        shares_inv = self._shares_inv(num_shares)
        rows = []
        for e in range(len(self.extra_const)):
            lin = np.stack([
                self._mont_const(int(v))
                for v in self.extra_lin[e]
            ])
            acc = field_sum(spec, spec.mul(meas, jnp.asarray(lin)),
                            axis=-2)
            const = self._mont_const(
                self.extra_const[e] * shares_inv)
            rows.append(spec.add(acc, jnp.broadcast_to(
                jnp.asarray(const), acc.shape)))
        return jnp.stack(rows, axis=-2)

    def _circuit_value(self, gouts: jax.Array, meas: jax.Array,
                       weights: Optional[jax.Array],
                       num_shares: int) -> jax.Array:
        """Reduce gadget outputs + extra rows to the single circuit
        value v (random linear combination when EVAL_OUTPUT_LEN > 1)."""
        spec = self.spec
        extra = self._extra_outputs(meas, num_shares)
        if self.kind == "count":
            return spec.sub(gouts[..., 0, :], meas[..., 0, :])
        if self.kind == "sum":
            outs = jnp.concatenate([gouts, extra], axis=-2)
        elif extra is None:   # SumVec
            return field_sum(spec, gouts, axis=-2)
        else:                 # Histogram / MultihotCountVec
            outs = jnp.concatenate(
                [field_sum(spec, gouts, axis=-2)[..., None, :], extra],
                axis=-2)
        assert weights is not None
        return field_sum(spec, spec.mul(weights, outs), axis=-2)

    # -- gadget evaluation on the call domain ----------------------

    def _gadget_outputs(self, coeffs: jax.Array) -> jax.Array:
        """Gadget polynomial (coeffs (..., 2p-1, n)) evaluated at
        alpha^1..alpha^C: alpha = omega_2p^2, so these are the even
        indices of the size-2p NTT."""
        batch = coeffs.shape[:-2]
        n = coeffs.shape[-1]
        padded = jnp.concatenate([
            coeffs,
            jnp.zeros(batch + (2 * self.p - self.coeff_len, n),
                      jnp.uint32)
        ], axis=-2)
        evals = self.ntt_2p(padded)
        idx = (2 * np.arange(1, self.calls + 1)).astype(np.int32)
        return evals[..., idx, :]

    # -- query ------------------------------------------------------

    def query(self, meas: jax.Array, proof: jax.Array,
              query_rand: jax.Array, joint_rand: Optional[jax.Array],
              num_shares: int = 2):
        """Batched Flp.query over plain-limb inputs.

        meas (..., MEAS_LEN, n), proof (..., PROOF_LEN, n), query_rand
        (..., QUERY_RAND_LEN, n), joint_rand (..., JOINT_RAND_LEN, n)
        or None.  Returns (verifier (..., VERIFIER_LEN, n) plain limbs,
        ok (...,) — False where t landed inside the NTT domain, the
        scalar layer's ValueError case).
        """
        spec = self.spec
        meas = spec.to_mont(meas)
        proof = spec.to_mont(proof)
        query_rand = spec.to_mont(query_rand)
        jr = spec.to_mont(joint_rand) if joint_rand is not None and \
            joint_rand.shape[-2] else None

        if self.eval_output_len > 1:
            weights = query_rand[..., :self.eval_output_len, :]
            t = query_rand[..., self.eval_output_len, :]
        else:
            weights = None
            t = query_rand[..., 0, :]

        seeds = proof[..., :self.arity, :]
        coeffs = proof[..., self.arity:, :]

        wires = self._wires(meas, jr, num_shares)
        wires = wires.at[..., 0, :].set(seeds)

        gouts = self._gadget_outputs(coeffs)
        v = self._circuit_value(gouts, meas, weights, num_shares)

        wire_coeffs = self.intt_p(wires)
        wire_at_t = poly_eval_mont(spec, wire_coeffs, t[..., None, :])
        gp_at_t = poly_eval_mont(spec, coeffs, t)

        verifier = jnp.concatenate(
            [v[..., None, :], wire_at_t, gp_at_t[..., None, :]],
            axis=-2)
        one = jnp.asarray(spec.ONE_MONT)
        ok = ~jnp.all(pow_static(spec, t, self.p) == one, axis=-1)
        return (spec.from_mont(verifier), ok)

    # -- decide -----------------------------------------------------

    def _gadget_eval(self, x: jax.Array) -> jax.Array:
        """The bare gadget on Montgomery inputs x (..., arity, n)."""
        spec = self.spec
        if self.gadget_kind == "mul":
            return spec.mul(x[..., 0, :], x[..., 1, :])
        if self.gadget_kind == "polyeval":
            # p(z) = z^2 - z  (circuits.py Sum)
            z = x[..., 0, :]
            return spec.sub(spec.mul(z, z), z)
        prod = spec.mul(x[..., 0::2, :], x[..., 1::2, :])
        return field_sum(spec, prod, axis=-2)

    def decide(self, verifier: jax.Array) -> jax.Array:
        """Batched Flp.decide over the summed verifier (plain limbs,
        (..., VERIFIER_LEN, n)) -> bool (...,)."""
        spec = self.spec
        v_zero = jnp.all(verifier[..., 0, :] == 0, axis=-1)
        x = spec.to_mont(verifier[..., 1:1 + self.arity, :])
        y = spec.to_mont(verifier[..., 1 + self.arity, :])
        consistent = jnp.all(self._gadget_eval(x) == y, axis=-1)
        return v_zero & consistent

    # -- prove ------------------------------------------------------

    def prove(self, meas: jax.Array, prove_rand: jax.Array,
              joint_rand: Optional[jax.Array]) -> jax.Array:
        """Batched Flp.prove over plain-limb inputs -> proof
        (..., PROOF_LEN, n) plain limbs."""
        spec = self.spec
        meas_m = spec.to_mont(meas)
        seeds = spec.to_mont(prove_rand)
        jr = spec.to_mont(joint_rand) if joint_rand is not None and \
            joint_rand.shape[-2] else None

        wires = self._wires(meas_m, jr, num_shares=1)
        wires = wires.at[..., 0, :].set(seeds)
        wire_coeffs = self.intt_p(wires)     # (..., A, p, n)

        batch = wires.shape[:-3]
        n = spec.num_limbs
        padded = jnp.concatenate([
            wire_coeffs,
            jnp.zeros(batch + (self.arity, self.p, n), jnp.uint32)
        ], axis=-2)
        wire_evals = self.ntt_2p(padded)     # (..., A, 2p, n)

        if self.gadget_kind == "mul":
            gp_evals = spec.mul(wire_evals[..., 0, :, :],
                                wire_evals[..., 1, :, :])
        elif self.gadget_kind == "polyeval":
            z = wire_evals[..., 0, :, :]
            gp_evals = spec.sub(spec.mul(z, z), z)
        else:
            prod = spec.mul(wire_evals[..., 0::2, :, :],
                            wire_evals[..., 1::2, :, :])
            gp_evals = field_sum(spec, prod, axis=-3)

        gp_coeffs = self.intt_2p(gp_evals)   # (..., 2p, n)
        proof = jnp.concatenate(
            [spec.from_mont(seeds),
             spec.from_mont(gp_coeffs[..., :self.coeff_len, :])],
            axis=-2)
        return proof
