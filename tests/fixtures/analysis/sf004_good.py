"""SF004 good fixture: egress goes through the wire.py codecs."""
from mastic_tpu import wire


def push(sock, key):
    sock.sendall(wire.frame(key))
