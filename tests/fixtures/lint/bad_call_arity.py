"""Known-bad: call not matching the callee signature (lint check 6)."""


def callee(a, b):
    return a + b


def caller():
    return callee(1, 2, 3)
