"""Known-bad: host cast of a traced value (TS002)."""

import jax
import jax.numpy as jnp


def to_int(x: jax.Array) -> int:
    return int(jnp.sum(x))


def to_scalar(x: jax.Array) -> float:
    return jnp.max(x).item()
