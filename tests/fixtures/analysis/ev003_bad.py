"""EV003: blocking sleep under a held lock in a non-blocking
context — the loop stalls AND every lock waiter queues behind it."""
import threading
import time

MU = threading.Lock()


def drain(sock):
    sock.setblocking(False)
    with MU:
        time.sleep(0.1)
