"""Known-bad: environment probe inside a function body (TS004)."""

import os

import jax


def pick_mode() -> bool:
    return jax.default_backend() == "cpu"


def lever() -> bool:
    return os.environ.get("MASTIC_FIXTURE_LEVER", "0") == "1"
