"""The durable admission WAL (ISSUE 18, `mastic_tpu/drivers/wal.py`):
record round-trip, segment rotation and compaction, the torn-tail
byte-boundary matrix, post-checksum corruption attribution, the
group-commit ack-after-fsync contract, the ENOSPC/fsync-failure
reason-coded brownout over real HTTP, and WAL-vs-snapshot dedup on
double-covered reports.

Fast tier throughout: the HTTP tests stop short of a page seal, so
nothing here triggers an XLA compile — the WAL sits strictly under
admission (decode + log + page append)."""

import json
import os
import time
from http.client import HTTPConnection

import pytest

from mastic_tpu.drivers import faults
from mastic_tpu.drivers.service import (CollectorService,
                                        ServiceConfig, TenantSpec)
from mastic_tpu.drivers.wal import (AdmissionWal, REASON_WAL_DEGRADED,
                                    REASON_WAL_FULL, WalConfig,
                                    WalUnavailable)
from mastic_tpu.mastic import MasticCount
from mastic_tpu.net import loadgen as loadgen_mod
from mastic_tpu.net.admission import NetConfig
from mastic_tpu.net.ingest import MEDIA_TYPE, UploadFront
from mastic_tpu.obs.registry import configure as configure_registry

CTX = b"wal test"
BITS = 2

ALWAYS = WalConfig(fsync="always")


def make_service(**over) -> tuple:
    m = MasticCount(BITS)
    vk = bytes(range(m.VERIFY_KEY_SIZE))
    spec = TenantSpec(name="count",
                      spec={"class": "MasticCount", "args": [BITS]},
                      ctx=CTX, verify_key=vk,
                      thresholds={"default": 1})
    defaults = dict(page_size=4, max_buffered=64,
                    epoch_deadline=600.0)
    defaults.update(over)
    svc = CollectorService([spec], config=ServiceConfig(**defaults))
    return (svc, m)


def put(port: int, path: str, body: bytes) -> tuple:
    conn = HTTPConnection("127.0.0.1", port, timeout=10.0)
    try:
        conn.request("PUT", path, body=body,
                     headers={"Content-Type": MEDIA_TYPE})
        resp = conn.getresponse()
        data = resp.read()
        return (resp.status, json.loads(data),
                dict(resp.getheaders()))
    finally:
        conn.close()


class FakeService:
    """Replay target that records submissions in order — enough of
    the CollectorService surface for recover(): `tenants`,
    `submit`, `report_digests`, `note_replayed`, `begin_epoch`."""

    def __init__(self, tenants=("count",), baseline=()):
        self.tenants = {t: object() for t in tenants}
        self.calls: list = []
        self.replayed: set = set()
        self._baseline = set(baseline)

    def submit(self, tenant, blob):
        self.calls.append(("submit", tenant, blob))
        return ("admitted", "")

    def report_digests(self, tenant):
        return set(self._baseline)

    def note_replayed(self, tenant, digest):
        self.replayed.add(digest)

    def begin_epoch(self, tenant):
        self.calls.append(("epoch", tenant, None))


def injector(spec: str) -> faults.FaultInjector:
    return faults.FaultInjector(faults.parse_faults(spec),
                                "collector")


# -- round-trip, rotation, compaction ---------------------------------

def test_roundtrip_replays_bit_identical(tmp_path):
    configure_registry()
    d = str(tmp_path / "wal")
    w = AdmissionWal(d, config=ALWAYS, fresh=True)
    blobs = [b"report-%d" % i for i in range(5)]
    for b in blobs:
        w.append_report("count", b)
    w.append_epoch_cut("count")
    w.append_report("count", b"after-cut")
    w.close()

    svc = FakeService()
    w2 = AdmissionWal(d, config=ALWAYS)
    counts = w2.recover(svc)
    assert counts["replayed"] == 6 and counts["epoch_cut"] == 1
    assert counts["torn_tail"] == 0 and counts["corrupt"] == 0
    assert [c[2] for c in svc.calls] == blobs + [None, b"after-cut"]
    assert svc.calls[5] == ("epoch", "count", None)
    # The watermark continues where the log left off.
    assert w2.append_report("count", b"next") == 7
    w2.close()


def test_append_before_recover_on_existing_dir_refused(tmp_path):
    d = str(tmp_path / "wal")
    w = AdmissionWal(d, config=ALWAYS, fresh=True)
    w.append_report("count", b"x")
    w.close()
    w2 = AdmissionWal(d, config=ALWAYS)
    with pytest.raises(RuntimeError):
        w2.append_report("count", b"y")
    w2.close()


def test_rotation_and_compaction(tmp_path):
    configure_registry()
    d = str(tmp_path / "wal")
    w = AdmissionWal(d, config=WalConfig(fsync="always",
                                         segment_bytes=1),
                     fresh=True)
    for i in range(4):
        w.append_report("count", b"r%d" % i)
    segs = sorted(n for n in os.listdir(d) if n.endswith(".seg"))
    assert len(segs) == 4          # 1-byte cap: every append rotates
    # A snapshot covering seq<=2 drops the sealed segments up to it
    # but never the live one.
    dropped = w.mark_covered(2, "deadbeef")
    assert dropped == 3
    remaining = sorted(n for n in os.listdir(d)
                       if n.endswith(".seg"))
    assert remaining == segs[3:]
    w.close()

    # Marker trusted (digest matches): only the uncovered record
    # replays.  Marker distrusted (digest differs): everything still
    # on disk replays — dedup falls back to the content digests.
    svc = FakeService()
    w2 = AdmissionWal(d, config=ALWAYS)
    counts = w2.recover(svc, snapshot_sha256="deadbeef")
    assert counts["replayed"] == 1 and counts["covered"] == 0
    assert [c[2] for c in svc.calls] == [b"r3"]
    w2.close()


def test_distrusted_marker_falls_back_to_digest_dedup(tmp_path):
    """A covered.json naming a DIFFERENT snapshot digest than the one
    actually restored must not be trusted: replay consults the
    service's report digests instead, so double-covered reports dedup
    rather than re-buffer (satellite: re-verify the snapshot digest
    before preferring it over replay)."""
    configure_registry()
    d = str(tmp_path / "wal")
    w = AdmissionWal(d, config=ALWAYS, fresh=True)
    w.append_report("count", b"covered-by-snapshot")
    w.append_report("count", b"not-in-snapshot")
    w.mark_covered(0, "digest-of-a-snapshot-we-do-NOT-have")
    w.close()

    from hashlib import sha256
    svc = FakeService(
        baseline=[sha256(b"covered-by-snapshot").digest()])
    w2 = AdmissionWal(d, config=ALWAYS)
    counts = w2.recover(svc, snapshot_sha256="something-else")
    assert counts["deduped"] == 1 and counts["replayed"] == 1
    assert counts["covered"] == 0
    assert [c[2] for c in svc.calls] == [b"not-in-snapshot"]
    # The deduped digest is armed for idempotent client retries.
    assert sha256(b"covered-by-snapshot").digest() in svc.replayed
    w2.close()


# -- torn tails and corruption ----------------------------------------

def test_torn_tail_byte_boundary_matrix(tmp_path):
    """Truncate the segment at EVERY byte boundary inside the last
    record: recovery must land on the exact good prefix (2 replayed,
    1 torn tail) at every cut, and on the clean boundary itself the
    tail is whole (no torn count)."""
    configure_registry()
    base = str(tmp_path / "base")
    w = AdmissionWal(base, config=ALWAYS, fresh=True)
    sizes = []
    seg = None
    for i in range(3):
        w.append_report("count", b"record-%d" % i)
        seg = w._seg_path
        sizes.append(os.path.getsize(seg))
    w.close()
    seg_name = os.path.basename(seg)

    import shutil
    for cut in range(sizes[1], sizes[2]):
        d = str(tmp_path / f"cut{cut}")
        shutil.copytree(base, d)
        os.truncate(os.path.join(d, seg_name), cut)
        svc = FakeService()
        w2 = AdmissionWal(d, config=ALWAYS)
        counts = w2.recover(svc)
        torn = 0 if cut == sizes[1] else 1
        assert (counts["replayed"], counts["torn_tail"]) == (2, torn), \
            f"cut={cut}: {counts}"
        assert [c[2] for c in svc.calls] == [b"record-0", b"record-1"]
        # The torn bytes are truncated away: a subsequent append must
        # start a valid record at the new tail, and a second recovery
        # sees a clean log.
        assert w2.append_report("count", b"record-2b") == 2
        w2.close()
        w3 = AdmissionWal(d, config=ALWAYS)
        again = w3.recover(FakeService())
        assert (again["replayed"], again["torn_tail"]) == (3, 0)
        w3.close()


def test_post_checksum_corruption_detected_and_skipped(tmp_path):
    """A bit flipped AFTER the CRC was computed (the on_disk corrupt
    fault) must be detected, counted, and skipped — never replayed as
    garbage, never refusing the rest of the log."""
    configure_registry()
    d = str(tmp_path / "wal")
    inj = injector("corrupt:party=collector:step=wal_append:nth=2"
                   ":offset=20:xor=1")
    w = AdmissionWal(d, config=ALWAYS, injector=inj, fresh=True)
    for i in range(3):
        w.append_report("count", b"record-%d" % i)
    w.close()
    svc = FakeService()
    w2 = AdmissionWal(d, config=ALWAYS)
    counts = w2.recover(svc)
    assert counts["corrupt"] == 1 and counts["replayed"] == 2
    assert [c[2] for c in svc.calls] == [b"record-0", b"record-2"]
    w2.close()


# -- the ack-after-fsync contract -------------------------------------

def test_group_commit_ack_waits_for_fsync(tmp_path):
    """With the group committer's fsync delayed by injection, the
    append call must not return before the delayed fsync completes —
    an ack can never precede durability."""
    configure_registry()
    delay = 0.3
    inj = injector(f"delay:party=collector:step=wal_fsync"
                   f":delay={delay}")
    w = AdmissionWal(str(tmp_path / "wal"),
                     config=WalConfig(fsync="group", group_ms=5.0),
                     injector=inj, fresh=True)
    t0 = time.monotonic()
    w.append_report("count", b"must-wait")
    waited = time.monotonic() - t0
    assert waited >= delay, \
        f"ack returned after {waited:.3f}s, fsync held {delay}s"
    stats = w.stats()
    assert stats["appends"] == 1
    assert stats["fsync_wait_ms_p99"] >= delay * 1000.0
    w.close()


def test_fsync_failure_degrades_then_heals(tmp_path):
    """An fsync error must fail the append (no ack on a lie) and flip
    the log to the reason-coded degraded state; the next append heals
    by rotating to a fresh segment."""
    configure_registry()
    inj = injector("fsync_error:party=collector:step=wal_fsync:nth=1")
    w = AdmissionWal(str(tmp_path / "wal"), config=ALWAYS,
                     injector=inj, fresh=True)
    with pytest.raises(WalUnavailable) as ei:
        w.append_report("count", b"doomed")
    assert ei.value.reason == REASON_WAL_DEGRADED
    assert ei.value.retry_after >= 1
    assert w.stats()["degraded"] == REASON_WAL_DEGRADED
    # nth=1 consumed: the retry rotates to a fresh segment and lands.
    assert w.append_report("count", b"healed") >= 0
    assert w.stats()["degraded"] is None
    w.close()


# -- brownout over real HTTP ------------------------------------------

def test_enospc_brownout_and_recover_over_http(tmp_path):
    """Injected ENOSPC on the WAL write: the upload gets a 503 with
    the `wal-full` reason and a Retry-After; the retry (disk space
    'freed' — the fault is one-shot) is admitted; the shed reason is
    counted on the tenant."""
    configure_registry()
    (svc, m) = make_service()
    inj = injector("enospc:party=collector:step=wal_append:nth=3")
    wal = AdmissionWal(str(tmp_path / "wal"), config=ALWAYS,
                       injector=inj, fresh=True)
    front = UploadFront(svc, config=NetConfig(),
                        persist=wal.append_report).start()
    try:
        blobs = loadgen_mod.build_blob_pool(m, CTX, 3, BITS,
                                            replay=1)
        for b in blobs[:2]:
            (code, body, _h) = put(front.port,
                                   "/v1/tenants/count/reports", b)
            assert (code, body) == (201, {"status": "admitted"})
        (code, body, headers) = put(front.port,
                                    "/v1/tenants/count/reports",
                                    blobs[2])
        assert code == 503
        assert body == {"error": "shed", "reason": REASON_WAL_FULL}
        assert int(headers["Retry-After"]) >= 1
        # Nothing half-admitted: the failed upload is not buffered.
        c = svc.metrics()["tenants"]["count"]["counters"]
        assert c["admitted"] == 2
        assert c["shed_reasons"] == {REASON_WAL_FULL: 1}
        # Retry lands (brownout healed by segment rotation).
        (code, body, _h) = put(front.port,
                               "/v1/tenants/count/reports", blobs[2])
        assert (code, body) == (201, {"status": "admitted"})
        assert svc.metrics()["tenants"]["count"]["counters"][
            "admitted"] == 3
    finally:
        front.stop()
        wal.close()
    # Everything acked is in the log — replay proves it.
    svc2 = FakeService()
    w2 = AdmissionWal(str(tmp_path / "wal"), config=ALWAYS)
    assert w2.recover(svc2)["replayed"] == 3
    w2.close()


def test_replay_scoped_dedup_on_live_service(tmp_path):
    """After recovery replays a report, the client's retry of the
    same blob acks idempotently without double-buffering — and the
    dedup set is REPLAY-scoped: a service that never recovered keeps
    admitting identical blobs (the loadgen reuses its pool)."""
    configure_registry()
    (svc, m) = make_service()
    blob = loadgen_mod.build_blob_pool(m, CTX, 1, BITS, replay=1)[0]
    # Never-recovered service: identical blobs are both admitted.
    assert svc.submit("count", blob)[0] == "admitted"
    assert svc.submit("count", blob)[0] == "admitted"
    c = svc.metrics()["tenants"]["count"]["counters"]
    assert c["admitted"] == 2

    (svc2, _m) = make_service()
    d = str(tmp_path / "wal")
    w = AdmissionWal(d, config=ALWAYS, fresh=True)
    w.append_report("count", blob)
    w.close()
    w2 = AdmissionWal(d, config=ALWAYS)
    assert w2.recover(svc2)["replayed"] == 1
    w2.close()
    # The retry of the replayed blob dedups; admitted stays 1.
    (status, detail) = svc2.submit("count", blob)
    assert (status, detail) == ("admitted", "duplicate")
    assert svc2.metrics()["tenants"]["count"]["counters"][
        "admitted"] == 1
