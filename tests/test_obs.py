"""Unified telemetry layer (ISSUE 7, mastic_tpu/obs/): span
mechanics, the metrics registry and its Prometheus export, the extra
schema gate, the live HTTP status surface, and the two behavioral
guarantees the tentpole claims — the trace reconstructs the
epoch -> round -> chunk hierarchy, and aggregates are bit-identical
with tracing on vs off.

Fast tier throughout (one small service epoch is the heaviest piece);
run via `make obs-smoke` (wired into `make ci`).
"""

import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from mastic_tpu.obs import devtime, registry as registry_mod, schema
from mastic_tpu.obs import trace as trace_mod
from mastic_tpu.obs.statusz import StatusServer, render_statusz


@pytest.fixture()
def tracer(tmp_path):
    """A private tracer singleton aimed at a temp JSONL file; the
    module singleton is restored (unsinked) afterwards."""
    path = tmp_path / "trace.jsonl"
    t = trace_mod.configure(trace_file=str(path))
    yield (t, path)
    trace_mod.configure(trace_file="")


@pytest.fixture()
def registry():
    reg = registry_mod.configure(max_label_sets=8)
    yield reg
    registry_mod.configure()


# -- span mechanics ----------------------------------------------------

def test_span_nesting_and_attributes(tracer):
    (t, _path) = tracer
    with t.span("epoch", tenant="a", epoch=0) as ep:
        with t.span("round", level=3) as rnd:
            with t.span("chunk.stage", chunk=1) as ch:
                pass
    spans = {s.name: s for s in t.spans()}
    assert spans["round"].parent_id == ep.span_id
    assert spans["chunk.stage"].parent_id == rnd.span_id
    assert spans["epoch"].attrs == {"tenant": "a", "epoch": 0}
    assert spans["chunk.stage"].attrs == {"chunk": 1}
    # children close before parents; every span got a duration
    assert all(s.duration_ms is not None for s in spans.values())
    assert spans["epoch"].duration_ms >= spans["round"].duration_ms


def test_span_events_carry_timestamps_and_attrs(tracer):
    (t, _path) = tracer
    with t.span("round") as sp:
        sp.event("retry", cause="timeout", attempt=1)
        time.sleep(0.002)
        sp.event("retry", cause="timeout", attempt=2)
    (e1, e2) = t.spans()[-1].events
    assert e1["attrs"]["attempt"] == 1
    assert e2["t_ms"] > e1["t_ms"]


def test_event_without_open_span_is_standalone(tracer):
    (t, _path) = tracer
    t.event("session_retry", kind="timeout")
    sp = t.spans()[-1]
    assert sp.name == "session_retry"
    assert sp.attrs["standalone_event"] is True


def test_detached_span_does_not_capture_siblings(tracer):
    (t, _path) = tracer
    ep_a = t.start_detached_span("epoch", tenant="a")
    ep_b = t.start_detached_span("epoch", tenant="b")
    with t.use_parent(ep_a):
        with t.span("round"):
            pass
    with t.use_parent(ep_b):
        with t.span("round"):
            pass
    t.end_span(ep_b)
    t.end_span(ep_a)
    rounds = [s for s in t.spans() if s.name == "round"]
    assert [r.parent_id for r in rounds] == [ep_a.span_id,
                                             ep_b.span_id]


def test_ring_buffer_eviction_is_counted():
    t = trace_mod.Tracer(capacity=3)
    for i in range(7):
        with t.span("s", i=i):
            pass
    assert len(t.spans()) == 3
    assert t.dropped() == 4
    assert t.finished() == 7
    # the ring keeps the newest spans
    assert [s.attrs["i"] for s in t.spans()] == [4, 5, 6]


def test_jsonl_round_trip_and_tree(tracer):
    (t, path) = tracer
    with t.span("epoch", tenant="a"):
        with t.span("round", level=0):
            with t.span("chunk.stage", chunk=0):
                pass
    spans = trace_mod.read_jsonl(str(path))
    assert [s["name"] for s in spans] == ["chunk.stage", "round",
                                          "epoch"]  # finish order
    tree = trace_mod.build_tree(spans)
    epoch = tree[None][0]
    assert epoch["name"] == "epoch"
    rnd = tree[epoch["span_id"]][0]
    assert rnd["name"] == "round"
    assert tree[rnd["span_id"]][0]["name"] == "chunk.stage"


def test_jsonl_torn_tail_line_skipped(tmp_path):
    path = tmp_path / "trace.jsonl"
    t = trace_mod.Tracer(trace_file=str(path))
    with t.span("a"):
        pass
    with open(path, "a") as f:
        f.write('{"name": "torn')   # killed mid-write
    spans = trace_mod.read_jsonl(str(path))
    assert [s["name"] for s in spans] == ["a"]


# -- registry ----------------------------------------------------------

def test_counter_gauge_histogram_values(registry):
    c = registry.counter("t_total", "help", tenant="a")
    c.inc()
    c.inc(4)
    assert c.value() == 5
    g = registry.gauge("t_gauge", "help", tenant="a")
    g.set(7)
    g.set(3)
    assert g.value() == 3
    h = registry.histogram("t_ms", "help", buckets=(10.0, 100.0),
                           phase="x")
    h.observe(5.0)
    h.observe(50.0)
    h.observe(5000.0)
    assert h.value() == {"count": 3, "sum": 5055.0}


def test_prometheus_text_golden(registry):
    """Exposition-format golden: HELP/TYPE headers, label quoting,
    cumulative histogram buckets with +Inf, _sum/_count."""
    registry.counter("g_total", "a counter", tenant="a").inc(2)
    h = registry.histogram("g_ms", "a histogram",
                           buckets=(10.0, 100.0), phase="up")
    h.observe(5.0)
    h.observe(50.0)
    h.observe(5000.0)
    expected = "\n".join([
        "# HELP g_ms a histogram",
        "# TYPE g_ms histogram",
        'g_ms_bucket{phase="up",le="10"} 1',
        'g_ms_bucket{phase="up",le="100"} 2',
        'g_ms_bucket{phase="up",le="+Inf"} 3',
        'g_ms_sum{phase="up"} 5055',
        'g_ms_count{phase="up"} 3',
        "# HELP g_total a counter",
        "# TYPE g_total counter",
        'g_total{tenant="a"} 2',
        "",
    ])
    assert registry.prometheus_text() == expected


def test_label_cardinality_cap_collapses_to_overflow(registry):
    for i in range(12):
        registry.counter("c_total", "h", tenant=f"t{i}").inc()
    snap = registry.snapshot()["c_total"]
    assert snap["overflowed"] == 4
    series = {json.dumps(s["labels"], sort_keys=True): s["value"]
              for s in snap["series"]}
    assert series['{"overflow": "true"}'] == 4
    assert len(snap["series"]) == 9   # 8 real + overflow child
    over = registry.snapshot()["mastic_obs_label_overflow_total"]
    assert over["series"][0]["labels"] == {"metric": "c_total"}
    assert over["series"][0]["value"] == 4


def test_declared_names_win_over_adhoc_help(registry):
    c = registry.counter("mastic_rounds_total", tenant="x")
    c.inc()
    text = registry.prometheus_text()
    assert "# HELP mastic_rounds_total aggregation rounds completed" \
        in text


def test_kind_mismatch_refused(registry):
    registry.counter("k_total", "h", tenant="a")
    with pytest.raises(ValueError, match="already registered"):
        registry.gauge("k_total", "h", tenant="a")


# -- the extra schema gate ---------------------------------------------

def _valid_chunk(i=0):
    return {"chunk": i, "stage_start_ms": 0.0, "stage_end_ms": 1.0,
            "collect_start_ms": 1.0, "collect_end_ms": 2.0,
            "phases": {"upload_ms": 0.1, "dispatch_ms": 0.2,
                       "compute_wait_ms": 0.3, "download_ms": 0.1,
                       "host_ms": 0.1},
            "host_syncs": 1, "reports": 4, "wall_ms": 2.0}


def test_schema_stamp_accepts_unified_record():
    extra = {
        "chunks": [_valid_chunk(0), _valid_chunk(1)],
        "pipeline": {"mode": "pipelined", "fallback": None,
                     "round_wall_ms": 4.0,
                     "overlap_efficiency": 0.4},
        "mesh": {"report_shards": 2, "psum_bytes_per_round": 128,
                 "shard_wait_skew_ms_p50": 0.0,
                 "shard_wait_skew_ms_max": 0.1},
        "service": {"tenant": "a", "epoch": 0,
                    "sched_overhead_ms": 0.2,
                    "buffered_reports": 0, "pending_epochs": 0},
    }
    schema.stamp(extra)
    assert extra["schema"] == schema.SCHEMA_VERSION


@pytest.mark.parametrize("mutate,needle", [
    (lambda e: e["chunks"][0].pop("wall_ms"), "missing wall_ms"),
    (lambda e: e["chunks"][0]["phases"].pop("host_ms"),
     "phases: missing host_ms"),
    (lambda e: e["pipeline"].pop("round_wall_ms"),
     "pipeline: missing"),
    (lambda e: e["pipeline"].__setitem__("mode", "warp"),
     "pipeline.mode"),
    (lambda e: e["service"].pop("tenant"), "service: missing"),
])
def test_schema_rejects_drifted_producers(mutate, needle):
    extra = {
        "chunks": [_valid_chunk()],
        "pipeline": {"mode": "serial", "fallback": "lever-off",
                     "round_wall_ms": 4.0,
                     "overlap_efficiency": 0.0},
        "service": {"tenant": "a", "epoch": 0,
                    "sched_overhead_ms": 0.2,
                    "buffered_reports": 0, "pending_epochs": 0},
    }
    mutate(extra)
    with pytest.raises(ValueError, match="schema violation"):
        schema.stamp(extra)


def test_round_metrics_validate_extra_stamps():
    from mastic_tpu.metrics import RoundMetrics

    mx = RoundMetrics(level=0, frontier_width=2, padded_width=4,
                      reports_total=3)
    mx.extra["pipeline"] = {"mode": "serial", "fallback": None,
                            "round_wall_ms": 1.0,
                            "overlap_efficiency": 0.0}
    mx.validate_extra()
    assert mx.extra["schema"] == schema.SCHEMA_VERSION


# -- devtime attribution -----------------------------------------------

def test_observe_round_feeds_histograms_and_split(registry):
    from mastic_tpu.metrics import RoundMetrics

    mx = RoundMetrics(level=0, frontier_width=2, padded_width=4,
                      reports_total=8, accepted=7)
    mx.rejected_eval_proof = 1
    mx.extra["round_wall_ms"] = 12.0
    chunk = _valid_chunk()
    chunk["phases"]["compile_ms"] = 100.0
    mx.extra["chunks"] = [chunk]
    devtime.observe_round(mx, tenant="t")
    assert registry.counter("mastic_rounds_total",
                            tenant="t").value() == 1
    assert registry.counter("mastic_reports_accepted_total",
                            tenant="t").value() == 7
    assert registry.counter("mastic_reports_rejected_total",
                            tenant="t",
                            check="eval_proof").value() == 1
    assert registry.counter("mastic_device_time_ms_total",
                            kind="compile").value() == 100.0
    # execute = dispatch + compute_wait
    assert registry.counter("mastic_device_time_ms_total",
                            kind="execute").value() == \
        pytest.approx(0.5)
    assert registry.histogram("mastic_round_wall_ms",
                              tenant="t").value()["count"] == 1


def test_jax_profile_lever_is_one_shot(monkeypatch):
    monkeypatch.setenv("MASTIC_JAX_PROFILE", "/tmp/profdir")
    devtime.reset_profile_lever()
    assert devtime.take_profile_dir() == "/tmp/profdir"
    assert devtime.take_profile_dir() is None
    devtime.reset_profile_lever()


# -- the live status surface over HTTP ---------------------------------

def _get(port, path):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}",
                timeout=10) as resp:
            return (resp.status, resp.read().decode())
    except urllib.error.HTTPError as exc:   # 404 raises in urllib
        return (exc.code, exc.read().decode())


def _count_reports(m, ctx, values, bits, seed=0):
    from mastic_tpu.drivers.service import encode_upload

    rng = np.random.default_rng(seed)
    blobs = []
    for v in values:
        alpha = m.vidpf.test_index_from_int(v, bits)
        nonce = bytes(rng.integers(0, 256, m.NONCE_SIZE,
                                   dtype="uint8"))
        rand = bytes(rng.integers(0, 256, m.RAND_SIZE,
                                  dtype="uint8"))
        (ps, shares) = m.shard(ctx, (alpha, True), nonce, rand)
        blobs.append(encode_upload(m, (nonce, ps, shares)))
    return blobs


def test_status_endpoints_during_live_smoke_epoch(registry, tracer):
    """/metrics and /statusz (and /varz) fetched over real HTTP
    between scheduler quanta of a live epoch — the snapshot-under-
    lock contract: the single-threaded scheduler publishes, the
    server thread only reads."""
    from mastic_tpu.drivers.service import (CollectorService,
                                            ServiceConfig, TenantSpec)
    from mastic_tpu.mastic import MasticCount

    bits = 2
    m = MasticCount(bits)
    vk = bytes(range(m.VERIFY_KEY_SIZE))
    svc = CollectorService(
        [TenantSpec(name="count",
                    spec={"class": "MasticCount", "args": [bits]},
                    ctx=b"obs", verify_key=vk,
                    thresholds={"default": 2}, chunk_size=2)],
        config=ServiceConfig(page_size=2, epoch_deadline=600.0))
    server = StatusServer(port=0).start()
    try:
        for blob in _count_reports(m, b"obs", [0, 0, 3, 3], bits):
            svc.submit("count", blob)
        svc.submit("count", b"malformed")   # one quarantine
        svc.begin_epoch("count")
        server.publish(svc.metrics())
        fetched_mid_epoch = False
        while svc.step():
            server.publish(svc.metrics())
            (code, text) = _get(server.port, "/metrics")
            assert code == 200
            fetched_mid_epoch = True
        server.publish(svc.metrics())
        assert fetched_mid_epoch

        (code, metrics_text) = _get(server.port, "/metrics")
        assert code == 200
        for needle in (
                'mastic_reports_admitted_total{tenant="count"} 4',
                'mastic_reports_quarantined_total'
                '{tenant="count",reason="malformed"} 1',
                'mastic_rounds_total{tenant="count"} 2',
                "mastic_chunk_phase_ms_bucket",
                'mastic_epochs_total{tenant="count",'
                'outcome="completed"} 1'):
            assert needle in metrics_text, (needle, metrics_text)

        (code, statusz) = _get(server.port, "/statusz")
        assert code == 200
        assert "tenant count" in statusz
        assert "admitted=4" in statusz

        (code, varz_text) = _get(server.port, "/varz")
        varz = json.loads(varz_text)
        assert varz["service"]["tenants"]["count"]["counters"][
            "admitted"] == 4
        assert varz["metrics"]["mastic_rounds_total"]["series"]
        assert varz["trace"]["finished"] > 0

        (code, _body) = _get(server.port, "/nosuch")
        assert code == 404

        # the trace reconstructs epoch -> round -> chunk for the
        # live epoch (the acceptance hierarchy)
        spans = trace_mod.read_jsonl(str(tracer[1]))
        epochs = list(trace_mod.walk(spans, "epoch"))
        rounds = list(trace_mod.walk(spans, "round"))
        assert len(epochs) == 1 and len(rounds) == 2
        assert all(r["parent_id"] == epochs[0]["span_id"]
                   for r in rounds)
        assert epochs[0]["attrs"]["tenant"] == "count"
        round_ids = {r["span_id"] for r in rounds}
        chunks = [s for s in spans
                  if s["name"].startswith("chunk.")]
        assert chunks and all(c["parent_id"] in round_ids
                              for c in chunks)
    finally:
        server.stop()


def test_render_statusz_empty_snapshot():
    assert "no snapshot published" in render_statusz({})


# -- the headline guarantee: tracing changes nothing -------------------

def test_aggregates_bit_identical_with_tracing_on_vs_off(tmp_path):
    """The whole telemetry layer is observe-only: a chunked
    heavy-hitters run with a JSONL sink + registry armed produces
    bit-identical results, metrics counters and checkpoint state to
    one with tracing pointed nowhere."""
    from mastic_tpu.drivers.heavy_hitters import (
        HeavyHittersRun, get_reports_from_measurements)
    from mastic_tpu.mastic import MasticCount

    m = MasticCount(3)
    vk = bytes(range(m.VERIFY_KEY_SIZE))
    reports = get_reports_from_measurements(
        m, b"onoff", [((False, True, False), 1),
                      ((True, True, True), 1),
                      ((True, True, True), 1)])

    def collect(trace_file):
        trace_mod.configure(trace_file=trace_file)
        registry_mod.configure()
        run = HeavyHittersRun(m, b"onoff", {"default": 2}, reports,
                              verify_key=vk, chunk_size=2)
        while run.step():
            pass
        counters = [
            {k: v for (k, v) in mx.as_dict().items() if k != "extra"}
            for mx in run.metrics]
        return (run.result(), counters, run.to_bytes())

    (res_on, counters_on, ckpt_on) = collect(
        str(tmp_path / "on.jsonl"))
    (res_off, counters_off, ckpt_off) = collect("")
    trace_mod.configure(trace_file="")
    registry_mod.configure()
    assert res_on == res_off
    assert counters_on == counters_off
    assert ckpt_on == ckpt_off   # byte-for-byte checkpoint equality
    # and the traced run really did write spans
    spans = trace_mod.read_jsonl(str(tmp_path / "on.jsonl"))
    assert any(s["name"] == "round" for s in spans)
