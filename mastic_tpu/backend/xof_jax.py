"""Batched VDAF XOFs on top of the JAX crypto kernels.

Mirrors the scalar constructions in mastic_tpu.xof (byte-exact):

* `XofTurboShake128`: TurboSHAKE128(le16(len(dst)) || dst ||
  le8(len(seed)) || seed || binder, domain 1).  All message lengths in
  Mastic are static protocol parameters, so messages are built by
  concatenating broadcast constant segments with per-lane arrays.

* `XofFixedKeyAes128`: fixed key = TurboSHAKE128(le16(len(dst)) || dst
  || binder, domain 2, 16); block i = pi(seed XOR le128(i)) with
  pi(x) = AES(sigma(x)) XOR sigma(x), sigma(lo||hi) = hi || hi^lo.
  One AES key schedule per (report, usage), shared across the whole
  prefix tree — the batched kernel amortizes it over every node.

Field-element sampling (`sample_vec`) reproduces the scalar rejection
sampler *assuming no rejection* and returns the in-range mask; callers
surface the mask so the driver can fall back to the scalar path for
the (~2^-32 per element) lanes where a rejection would have shifted
the stream.
"""

import jax
import jax.numpy as jnp
import numpy as np

from ..common import to_le_bytes
from ..ops.aes_jax import (aes128_encrypt, aes128_encrypt_bitsliced,
                           aes128_key_schedule, bitslice_keys,
                           bitslice_pack, bitslice_unpack)
from ..ops.field_jax import FieldSpec
from ..ops.keccak_jax import turbo_shake128

_U8 = jnp.uint8


def const_bytes(data: bytes) -> np.ndarray:
    return np.frombuffer(data, dtype=np.uint8)


def build_msg(batch_shape: tuple, *parts) -> jax.Array:
    """Concatenate message parts along the last axis.  Parts are bytes /
    np.uint8 constants (broadcast across the batch) or arrays with
    leading dims broadcastable to `batch_shape`."""
    arrs = []
    for part in parts:
        if isinstance(part, (bytes, bytearray)):
            part = const_bytes(bytes(part))
        if isinstance(part, np.ndarray):
            part = jnp.asarray(part, _U8)
        if part.shape[-1] == 0:
            continue
        arrs.append(jnp.broadcast_to(part, batch_shape + (part.shape[-1],)))
    if not arrs:
        return jnp.zeros(batch_shape + (0,), _U8)
    return jnp.concatenate(arrs, axis=-1)


def ts_prefix(dst: bytes, seed_len: int) -> bytes:
    """The static XofTurboShake128 message prefix for a given dst and
    seed length (scalar reference: mastic_tpu/xof.py:55-65)."""
    return to_le_bytes(len(dst), 2) + dst + to_le_bytes(seed_len, 1)


def turboshake_xof(dst: bytes, seed, binder_parts: tuple, out_len: int,
                   batch_shape: tuple) -> jax.Array:
    """Batched XofTurboShake128(seed, dst, binder).next(out_len).
    `seed` and each binder part may be a constant bytes or an array."""
    seed_len = len(seed) if isinstance(seed, (bytes, bytearray)) \
        else seed.shape[-1]
    msg = build_msg(batch_shape, ts_prefix(dst, seed_len), seed,
                    *binder_parts)
    return turbo_shake128(msg, 1, out_len)


def fixed_key_schedule(dst: bytes, binder, batch_shape: tuple) -> jax.Array:
    """Derive the per-(dst, binder) fixed AES key and expand it:
    -> round keys (..., 11, 16)."""
    msg = build_msg(batch_shape, to_le_bytes(len(dst), 2) + dst, binder)
    keys = turbo_shake128(msg, 2, 16)
    return aes128_key_schedule(keys)


_BLOCK_INDEX_CACHE: dict[int, np.ndarray] = {}


def _block_indices(num_blocks: int) -> np.ndarray:
    """le128(i) for i in range(num_blocks): (num_blocks, 16) uint8."""
    cached = _BLOCK_INDEX_CACHE.get(num_blocks)
    if cached is None:
        cached = np.zeros((num_blocks, 16), np.uint8)
        for i in range(num_blocks):
            cached[i] = const_bytes(to_le_bytes(i, 16))
        _BLOCK_INDEX_CACHE[num_blocks] = cached
    return cached


def fixed_key_blocks(round_keys: jax.Array, seeds: jax.Array,
                     num_blocks: int) -> jax.Array:
    """XofFixedKeyAes128 output blocks 0..num_blocks-1.

    round_keys: (B..., 11, 16); seeds: (B..., N..., 16) where the lead
    dims of `seeds` start with the dims of `round_keys` (one key
    schedule per report, many seeds per report).  Returns
    (B..., N..., num_blocks*16) uint8.

    Large report batches take the bitsliced AES path (32 reports per
    uint32 word along the batch axis); small ones keep the byte-plane
    circuit, which has no packing overhead.  Both are byte-exact
    (tests/test_ops_aes.py locks them against each other and the
    scalar layer).
    """
    x = seeds[..., None, :] ^ jnp.asarray(_block_indices(num_blocks))
    lo = x[..., :8]
    hi = x[..., 8:]
    sigma = jnp.concatenate([hi, hi ^ lo], axis=-1)
    if (round_keys.ndim == 3 and seeds.ndim >= 2
            and seeds.shape[0] == round_keys.shape[0]
            and round_keys.shape[0] >= 32):
        enc = _encrypt_bitsliced_reports(round_keys, sigma)
    else:
        # Broadcast round keys across per-report seed dims + block dim.
        extra = sigma.ndim - round_keys.ndim + 1
        rk = round_keys.reshape(
            round_keys.shape[:-2] + (1,) * extra + round_keys.shape[-2:])
        enc = aes128_encrypt(rk, sigma)
    out = enc ^ sigma
    return out.reshape(out.shape[:-2] + (num_blocks * 16,))


_BLOCK_PLANES_CACHE: dict[int, np.ndarray] = {}


def _block_planes(num_blocks: int) -> np.ndarray:
    from ..ops.aes_jax import block_index_planes

    cached = _BLOCK_PLANES_CACHE.get(num_blocks)
    if cached is None:
        cached = block_index_planes(num_blocks)
        _BLOCK_PLANES_CACHE[num_blocks] = cached
    return cached


def fixed_key_blocks_planes(key_planes: jax.Array, seed_planes: jax.Array,
                            num_blocks: int) -> jax.Array:
    """XofFixedKeyAes128 blocks entirely in the bitsliced plane domain.

    key_planes: (11, 8, 16, W) from bitslice_keys; seed_planes:
    (8, 16, N..., W).  Returns stream planes (8, 16, N..., num_blocks,
    W).  The Davies-Meyer construction's byte moves (x = seed ^
    le128(i); sigma = hi || hi^lo; out = E(sigma) ^ sigma) are all
    plane-index arithmetic — no pack/unpack at this boundary, which is
    the point: a level step stays bit-transposed from the parent seeds
    to the next seeds."""
    idx = jnp.asarray(_block_planes(num_blocks))   # (m, 8, 16)
    extra = seed_planes.ndim - 3
    idx = jnp.moveaxis(idx, 0, -1).reshape(
        (8, 16) + (1,) * extra + (num_blocks, 1))
    x = seed_planes[..., None, :] ^ idx            # (8, 16, N..., m, W)
    lo = x[:, :8]
    hi = x[:, 8:]
    sigma = jnp.concatenate([hi, hi ^ lo], axis=1)
    return aes128_encrypt_bitsliced(key_planes, sigma) ^ sigma


def _encrypt_bitsliced_reports(round_keys: jax.Array,
                               sigma: jax.Array) -> jax.Array:
    """AES over (R, N..., 16) blocks with per-report keys (R, 11, 16),
    bit-transposed along the report axis (padded to a multiple of 32
    with zero lanes, sliced back after)."""
    r = sigma.shape[0]
    pad = (-r) % 32
    if pad:
        sigma = jnp.concatenate(
            [sigma, jnp.zeros((pad,) + sigma.shape[1:], _U8)])
        round_keys = jnp.concatenate(
            [round_keys, jnp.zeros((pad, 11, 16), _U8)])
    planes = bitslice_pack(sigma)        # (8, 16, N..., W)
    kp = bitslice_keys(round_keys)       # (11, 8, 16, W)
    enc = bitslice_unpack(aes128_encrypt_bitsliced(kp, planes))
    return enc[:r] if pad else enc


def sample_vec(spec: FieldSpec, stream: jax.Array, length: int,
               offset: int = 0) -> tuple[jax.Array, jax.Array]:
    """Read `length` field elements from XOF output bytes starting at
    `offset`: -> (plain limbs (..., length, n), in_range (...)).

    Byte-exact vs the scalar rejection sampler when no rejection
    occurs; the returned mask is False for lanes where any element fell
    outside the field (caller falls back to the scalar path there).
    """
    size = spec.encoded_size
    data = stream[..., offset:offset + length * size]
    data = data.reshape(data.shape[:-1] + (length, size))
    (limbs, ok) = spec.limbs_from_le_bytes(data)
    return (limbs, jnp.all(ok, axis=-1))
