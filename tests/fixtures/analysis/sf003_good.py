"""SF003 good fixture: only the (public) length is recorded."""


def record_round(tracer, seed):
    tracer.event("round", seed_len=len(seed))
