"""Known-good: one allow above a multi-line statement covers it."""

TABLE = tuple(range(256))


def paired(key: bytes) -> int:
    # mastic-allow: SF002 — fixture: the allow above this two-line
    # statement must cover the finding on its continuation line too
    total = (TABLE[key[0]]
             + TABLE[key[1]])
    return total
