"""Incremental cross-round evaluation: byte-equality with the
from-root batched path, and the runtime-length sponge vs the static
one."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

pytestmark = pytest.mark.slow

from mastic_tpu import MasticCount, MasticSum
from mastic_tpu.backend.incremental import (IncrementalMastic, RoundPlan,
                                            round_inputs)
from mastic_tpu.backend.mastic_jax import BatchedMastic
from mastic_tpu.drivers.heavy_hitters import (compute_heavy_hitters,
                                              get_reports_from_measurements)
from mastic_tpu.oracle import weighted_heavy_hitters
from mastic_tpu.ops.keccak_jax import (turbo_shake128,
                                       turbo_shake128_dynamic)

CTX = b"incremental test"
VK = bytes(range(32))


def test_dynamic_sponge_matches_static():
    rng = np.random.default_rng(0)
    msg = rng.integers(0, 256, (3, 400), dtype=np.uint8)
    fn = jax.jit(lambda m, ln: turbo_shake128_dynamic(m, ln, 1, 32))
    for length in [0, 1, 17, 167, 168, 169, 200, 335, 336, 399, 400]:
        want = np.asarray(turbo_shake128(
            jnp.asarray(msg[:, :length]), 1, 32))
        got = np.asarray(fn(jnp.asarray(msg), jnp.int32(length)))
        np.testing.assert_array_equal(got, want, err_msg=str(length))


def _reports(mastic, values, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for (v, w) in values:
        alpha = mastic.vidpf.test_index_from_int(v, mastic.vidpf.BITS)
        nonce = rng.integers(0, 256, 16, dtype=np.uint8).tobytes()
        rand = rng.integers(0, 256, mastic.RAND_SIZE,
                            dtype=np.uint8).tobytes()
        out.append((nonce,) + mastic.shard(CTX, (alpha, w), nonce, rand))
    return out


def test_incremental_eval_proof_matches_from_root():
    """Per level, the engine's eval proofs must equal the from-root
    batched prep's (wire-exact binder assembly across the carry)."""
    mastic = MasticCount(4)
    bm = BatchedMastic(mastic)
    reports = _reports(mastic, [(0b1010, 1), (0b1011, 1), (0b0001, 1)])
    batch = bm.marshal_reports(reports)
    num = len(reports)

    engine = IncrementalMastic(bm, width=8)
    (ext_rk, conv_rk) = bm.vidpf.roundkeys(CTX, batch.nonces)
    carries = [engine.init_carry(num, batch.keys[:, a], a)
               for a in range(2)]
    layouts: list = []

    # A pruned frontier path: keep only prefixes under 10*.
    frontiers = [
        [(False,), (True,)],
        [(True, False), (True, True)],
        [(True, False, True), (True, False, False)],
        [(True, False, True, False), (True, False, True, True)],
    ]
    for (level, prefixes) in enumerate(frontiers):
        plan = RoundPlan(tuple(prefixes), level, 4, 8, layouts)
        rnd = round_inputs(plan)
        proofs = []
        outs = []
        for a in range(2):
            (carries[a], proof, out, ok) = jax.jit(
                lambda c, r, agg=a: engine.agg_round(
                    agg, VK, CTX, c, r, ext_rk, conv_rk, batch.cws))(
                carries[a], rnd)
            assert bool(np.all(np.asarray(ok)))
            proofs.append(np.asarray(proof))
            outs.append(np.asarray(out))
        layouts.append(plan.layout_new)

        # From-root reference for the same agg param.
        agg_param = (level, tuple(prefixes), False)
        (p0, p1) = bm.prep_both(VK, CTX, agg_param, batch)
        np.testing.assert_array_equal(proofs[0],
                                      np.asarray(p0.eval_proof),
                                      err_msg=f"level {level} agg 0")
        np.testing.assert_array_equal(proofs[1],
                                      np.asarray(p1.eval_proof),
                                      err_msg=f"level {level} agg 1")
        rows = len(prefixes) * (1 + mastic.flp.OUTPUT_LEN)
        np.testing.assert_array_equal(
            outs[0][:, :rows], np.asarray(p0.out_share),
            err_msg=f"level {level} out 0")
        np.testing.assert_array_equal(
            outs[1][:, :rows], np.asarray(p1.out_share),
            err_msg=f"level {level} out 1")


@pytest.mark.parametrize("make,values,threshold", [
    (lambda: MasticCount(5),
     [(0b10101, 1)] * 3 + [(0b10110, 1)] * 2 + [(0b00101, 1)], 3),
    (lambda: MasticSum(4, 7),
     [(0b1010, 3), (0b1010, 4), (0b0110, 7), (0b0001, 1)], 7),
])
def test_heavy_hitters_incremental_matches_from_root(make, values,
                                                     threshold):
    mastic = make()
    reports = get_reports_from_measurements(
        mastic, CTX,
        [(mastic.vidpf.test_index_from_int(v, mastic.vidpf.BITS), w)
         for (v, w) in values])
    thresholds = {"default": threshold}
    got_inc = compute_heavy_hitters(mastic, CTX, thresholds, reports,
                                    verify_key=VK, incremental=True)
    got_root = compute_heavy_hitters(mastic, CTX, thresholds, reports,
                                     verify_key=VK, incremental=False)
    oracle = weighted_heavy_hitters(
        [(mastic.vidpf.test_index_from_int(v, mastic.vidpf.BITS), w)
         for (v, w) in values], threshold, mastic.vidpf.BITS)
    assert got_inc == got_root == oracle
