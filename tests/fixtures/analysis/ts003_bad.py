"""Known-bad: numpy applied to a traced value (TS003)."""

import jax
import jax.numpy as jnp
import numpy as np


def mixed(x: jax.Array):
    y = jnp.abs(x)
    return np.sum(y)
