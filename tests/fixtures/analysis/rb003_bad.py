"""Known-bad: report-batched upload via bare device_put (RB003)."""

import jax
from jax import device_put


def upload_chunk(mesh, batch, carry):
    # Lands the whole chunk on one device: a mesh round would reshard
    # it through a layout mismatch instead of streaming per-shard.
    dev_batch = jax.device_put(batch)
    dev_carry = device_put(carry)
    return (dev_batch, dev_carry)
