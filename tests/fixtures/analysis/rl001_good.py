"""RL001 clean: every raise between acquisition and handoff closes
the socket before propagating."""
import socket


def dial(host, port):
    sock = socket.create_connection((host, port))
    try:
        sock.settimeout(5.0)
    except BaseException:
        sock.close()
        raise
    return sock
