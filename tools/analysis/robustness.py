"""Pass 5 — robustness of the session/driver/network layer.

Scope: mastic_tpu/drivers/ and mastic_tpu/net/ — the layers that own
sockets, subprocess lifecycles, the HTTP upload front, and fault
handling (ISSUE 3; net/ since ISSUE 11 — a network-facing door has
exactly these failure modes, at internet exposure).  Failure modes
this pass keeps out of the tree:

  RB001  a blocking socket read with no deadline.  Flags calls to
         `.accept()` / `.recv()` / `.makefile()` in a scope that
         never arms a timeout (`settimeout` on the same root object,
         or a `timeout=` keyword on the call itself), plus
         `create_connection` without a `timeout=`.  `makefile()` is
         flagged unconditionally: the file wrapper has no usable
         deadline story (a timeout mid-read leaves its buffer
         inconsistent), and the drivers' Channel replaces it.

  RB002  an `except` block that swallows the error: a handler whose
         body is only `pass` / `continue` / `break` / `...` —
         no re-raise, no structured report (a call, return or
         assignment that records the outcome).  Silent except blocks
         are how a faulted session degrades invisibly instead of
         landing in a counter.

  RB003  a direct `device_put` in drivers/.  Report-batched uploads
         must route through `parallel.mesh.place_reports` /
         `place_replicated` (which carry the mesh's NamedSharding):
         a bare `jax.device_put` silently lands the array on ONE
         device, so a mesh-sharded round would replicate-or-gather
         it through a layout mismatch instead of streaming per-shard
         — exactly the class of bug the r10 mesh executor's
         bit-identity tests cannot see (the math still comes out
         right, only the placement and the interconnect traffic go
         wrong).  Genuinely single-device puts carry an allow.

  RB004  unbounded buffer growth in the long-lived layer (ISSUE 6):
         a `queue.Queue()` / `collections.deque()` constructed with
         no capacity bound, or an `.append(...)` inside a
         constant-true `while` loop with neither a `break` nor a
         `len(...)` bound check in the loop — a continuously
         ingesting collector that buffers without a quota or shed
         policy converts overload into an OOM kill instead of a
         counted, policied shed (drivers/service.py's admission
         contract).

  RB005  a deadline-less `while` loop inside a service/scheduler
         class (a ClassDef whose name contains "Service" or
         "Scheduler"): the loop's test+body reference nothing
         deadline-shaped (an identifier containing "deadline", or a
         call to `.expired()` / `.remaining()`), so a wedged epoch
         or a never-draining queue spins the loop forever with no
         bounded exit.  Loops bounded by construction carry an
         allow naming the bound.

  RB006  a publish-by-rename without durability (ISSUE 18): an
         `os.replace` / `os.rename` in a scope that never calls any
         fsync (`os.fsync`, `fsync_dir`, ...).  Rename makes a file
         *visible* atomically but not *durable* — after a crash the
         final name can hold an empty or torn file, exactly the
         state the WAL recovery and `--resume` snapshot loading must
         never be handed.  The sanctioned idiom is tmp-write →
         flush+fsync(file) → os.replace → fsync(directory).

Intentional exceptions are suppressed inline with a justified
`# mastic-allow: RB00x — reason`, same as every other pass.
"""

import ast

from .core import Finding, root_name

PASS_NAME = "robustness"

RULES = {
    "RB001": "blocking socket read (or ssl handshake) without a "
             "deadline",
    "RB002": "except block swallows the error without re-raise or "
             "structured report",
    "RB003": "direct device_put in drivers/ bypasses "
             "place_reports' mesh placement",
    "RB004": "unbounded queue/list growth without a capacity bound "
             "or shed policy",
    "RB005": "deadline-less while loop in service scheduler code",
    "RB006": "os.replace/os.rename without an fsync in scope — "
             "rename publishes, fsync makes durable",
}

SCOPE_PREFIXES = ("mastic_tpu/drivers/", "mastic_tpu/net/")

# The service/load CLIs live in tools/ but own the same
# long-lived-loop failure modes the drivers do; the standalone
# network party and the cert minter (ISSUE 14) own sockets and TLS
# handshakes at the same exposure.
EXTRA_FILES = ("tools/serve.py", "tools/loadgen.py",
               "tools/party.py", "tools/certs.py")

# `do_handshake` (ISSUE 14): an ssl handshake on a socket with no
# armed timeout blocks on a silent peer exactly like a bare recv —
# the tls_handshake chaos checkpoint exists because this stall is a
# real attack surface.
_BLOCKING_READS = {"accept", "recv", "recv_into", "makefile",
                   "do_handshake"}
_CONNECT_FNS = {"create_connection"}


def in_scope(rel: str) -> bool:
    return rel.startswith(SCOPE_PREFIXES) or rel in EXTRA_FILES


def _scopes(tree: ast.Module):
    """Every function scope plus the module body (socket code at
    module level is in scope too)."""
    yield tree
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _scope_statements(scope):
    """Nodes of this scope only (nested function bodies are their own
    scopes; their timeouts don't arm this one's reads)."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
            stack.extend(ast.iter_child_nodes(node))


def _has_timeout_kw(call: ast.Call) -> bool:
    return any(kw.arg in ("timeout", "deadline")
               for kw in call.keywords)


def _check_rb001(info, findings) -> None:
    for scope in _scopes(info.tree):
        nodes = list(_scope_statements(scope))
        armed = set()
        for node in nodes:
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "settimeout":
                armed.add(root_name(node.func.value))
        for node in nodes:
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Attribute):
                attr = node.func.attr
                if attr not in _BLOCKING_READS:
                    continue
                if attr == "accept" and (node.args or node.keywords):
                    # socket.accept() takes no arguments; a call with
                    # some is a different accept (e.g. the session
                    # layer's deadline-bounded wrapper).
                    continue
                if attr == "makefile":
                    findings.append(Finding(
                        "RB001", info.rel, node.lineno,
                        "socket.makefile() read path has no usable "
                        "deadline — use the drivers' Channel"))
                    continue
                root = root_name(node.func.value)
                if root in armed or _has_timeout_kw(node):
                    continue
                findings.append(Finding(
                    "RB001", info.rel, node.lineno,
                    f"blocking .{attr}() with no deadline: no "
                    f"settimeout on '{root or '<expr>'}' in this "
                    f"scope and no timeout= on the call"))
            elif isinstance(node.func, ast.Name) \
                    and node.func.id in _CONNECT_FNS:
                if not _has_timeout_kw(node):
                    findings.append(Finding(
                        "RB001", info.rel, node.lineno,
                        f"{node.func.id}() without timeout= blocks "
                        f"until the kernel gives up"))
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _CONNECT_FNS:
                if not _has_timeout_kw(node):
                    findings.append(Finding(
                        "RB001", info.rel, node.lineno,
                        f"{node.func.attr}() without timeout= blocks "
                        f"until the kernel gives up"))


def _swallows(handler: ast.ExceptHandler) -> bool:
    """True when every statement of the handler body is inert: no
    raise, no call, no return/assign that could record the outcome."""
    for stmt in handler.body:
        if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
            continue
        if isinstance(stmt, ast.Expr) \
                and isinstance(stmt.value, ast.Constant):
            continue  # docstring / ellipsis
        return False
    return True


def _check_rb002(info, findings) -> None:
    for node in ast.walk(info.tree):
        if isinstance(node, ast.ExceptHandler) and _swallows(node):
            what = ("bare except" if node.type is None
                    else ast.unparse(node.type)[:40])
            findings.append(Finding(
                "RB002", info.rel, node.lineno,
                f"except ({what}) swallows the error — re-raise, or "
                f"record it (counter/log/return)"))


def _check_rb003(info, findings) -> None:
    """Flag `device_put` calls however spelled (jax.device_put, a
    bare imported device_put) — the drivers' sanctioned upload paths
    are parallel.mesh.place_reports / place_replicated, which carry
    the installed mesh's NamedSharding."""
    for node in ast.walk(info.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = (f.attr if isinstance(f, ast.Attribute)
                else f.id if isinstance(f, ast.Name) else None)
        if name != "device_put":
            continue
        findings.append(Finding(
            "RB003", info.rel, node.lineno,
            "direct device_put bypasses place_reports — when a mesh "
            "is installed this lands the array on one device and the "
            "round pays a layout reshard instead of streaming "
            "per-shard; route report-batched uploads through "
            "parallel.mesh.place_reports (replicated scalars through "
            "place_replicated), or allow a genuinely single-device "
            "put"))


_QUEUE_CTORS = {"Queue", "LifoQueue", "PriorityQueue", "SimpleQueue",
                "deque"}
_QUEUE_BOUND_KWS = {"maxsize", "maxlen"}


def _const_true(test: ast.AST) -> bool:
    return isinstance(test, ast.Constant) and bool(test.value)


def _check_rb004(info, findings) -> None:
    """Unbounded growth: capacity-less queue constructions, and
    appends inside a constant-true loop with no break and no len()
    bound check anywhere in the loop."""
    for node in ast.walk(info.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = (f.attr if isinstance(f, ast.Attribute)
                else f.id if isinstance(f, ast.Name) else None)
        if name not in _QUEUE_CTORS:
            continue
        bounded = any(
            kw.arg in _QUEUE_BOUND_KWS
            and not (isinstance(kw.value, ast.Constant)
                     and kw.value.value in (None, 0))
            for kw in node.keywords)
        if name == "deque":
            bounded = bounded or len(node.args) >= 2
        else:
            bounded = bounded or (
                node.args
                and not (isinstance(node.args[0], ast.Constant)
                         and node.args[0].value in (None, 0)))
        if not bounded:
            findings.append(Finding(
                "RB004", info.rel, node.lineno,
                f"{name}() without a capacity bound grows without "
                f"limit under sustained ingest — pass maxsize/maxlen "
                f"(and shed on full), or allow with the reason the "
                f"producer is bounded"))
    for node in ast.walk(info.tree):
        if not isinstance(node, ast.While) \
                or not _const_true(node.test):
            continue
        (appends, has_break, has_bound) = ([], False, False)
        for sub in ast.walk(node):
            if isinstance(sub, ast.Break):
                has_break = True
            elif isinstance(sub, ast.Call):
                f = sub.func
                if isinstance(f, ast.Attribute) \
                        and f.attr == "append":
                    appends.append(sub)
                elif isinstance(f, ast.Name) and f.id == "len":
                    has_bound = True
        if appends and not has_break and not has_bound:
            findings.append(Finding(
                "RB004", info.rel, appends[0].lineno,
                "append inside a `while True` loop with no break and "
                "no len() bound check — unbounded buffer growth; "
                "bound the buffer (shed policy) or exit the loop"))


_DEADLINE_CALLS = {"expired", "remaining"}


def _references_deadline(loop: ast.While) -> bool:
    for sub in ast.walk(loop):
        if isinstance(sub, ast.Name) \
                and "deadline" in sub.id.lower():
            return True
        if isinstance(sub, ast.Attribute) \
                and ("deadline" in sub.attr.lower()
                     or sub.attr in _DEADLINE_CALLS):
            return True
    return False


def _check_rb005(info, findings) -> None:
    """Deadline-less while loops inside Service/Scheduler classes —
    the long-lived scheduler layer where every loop needs a bounded
    exit (drivers/service.py's contract)."""
    for node in ast.walk(info.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        if "Service" not in node.name and "Scheduler" not in node.name:
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.While) \
                    and not _references_deadline(sub):
                findings.append(Finding(
                    "RB005", info.rel, sub.lineno,
                    f"while loop in {node.name} references no "
                    f"deadline (no *deadline* identifier, no "
                    f".expired()/.remaining() call) — a wedged epoch "
                    f"spins it forever; thread a Deadline through, "
                    f"or allow naming the structural bound"))


_RENAME_FNS = {"replace", "rename"}


def _call_name(node: ast.Call) -> str:
    f = node.func
    return (f.attr if isinstance(f, ast.Attribute)
            else f.id if isinstance(f, ast.Name) else "")


def _check_rb006(info, findings) -> None:
    """Publish-by-rename without durability: flag `os.replace` /
    `os.rename` in any scope that never calls an fsync — `os.fsync`,
    the WAL's `fsync_dir`, or any wrapper whose name carries
    "fsync".  Scope-level, like RB001's timeout arming: the fsync
    that makes the tmp file durable must live next to the rename
    that publishes it, not in some caller the analyzer can't see."""
    for scope in _scopes(info.tree):
        nodes = [n for n in _scope_statements(scope)
                 if isinstance(n, ast.Call)]
        if any("fsync" in _call_name(n) for n in nodes):
            continue
        for node in nodes:
            if not isinstance(node.func, ast.Attribute) \
                    or node.func.attr not in _RENAME_FNS \
                    or root_name(node.func.value) != "os":
                continue
            findings.append(Finding(
                "RB006", info.rel, node.lineno,
                f"os.{node.func.attr}() with no fsync in this scope "
                f"— rename publishes the name atomically but not "
                f"durably; a crash can leave an empty or torn file "
                f"under the final name.  fsync the tmp file before "
                f"the rename (and the directory after), or allow "
                f"naming the durability story"))


def check(info) -> list:
    findings: list = []
    _check_rb001(info, findings)
    _check_rb002(info, findings)
    _check_rb003(info, findings)
    _check_rb004(info, findings)
    _check_rb005(info, findings)
    _check_rb006(info, findings)
    seen = set()
    out = []
    for f in findings:
        if f.key() in seen:
            continue
        seen.add(f.key())
        out.append(f)
    return out
