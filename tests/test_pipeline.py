"""Pipelined chunk-streaming executor (ISSUE 4, `MASTIC_PIPELINE`):
serial bit-identity (agg shares, metrics counters, checkpoint state)
across 1/2/3-chunk stores with a partial tail, measured overlap via
the phase timeline under injected store latency, ahead-of-time bucket
compilation (zero inline compile for predicted buckets, correct
inline compile on misses), the two-chunks-in-flight envelope term
with the degrade-to-serial budget fallback, and composition with
checkpoint kill-resume under an armed `MASTIC_FAULTS` lever.

Fast tier (run via `make pipeline`, wired into `make ci`); the
process-separated session composition runs in the slow tier.
"""

import io
import time

import numpy as np
import pytest

from mastic_tpu.backend.mastic_jax import BatchedMastic
from mastic_tpu.common import gen_rand
from mastic_tpu.drivers import pipeline
from mastic_tpu.drivers.chunked import (PIPELINE_CHUNKS_IN_FLIGHT,
                                        HostReportStore,
                                        memory_envelope,
                                        round_peak_bytes)
from mastic_tpu.drivers.heavy_hitters import (
    HeavyHittersRun, get_reports_from_measurements)
from mastic_tpu.mastic import MasticCount

CTX = b"pipeline test"


# NOTE: the suite runs with the persistent XLA compile cache OFF
# (tests/conftest.py): on this CPU fabric, reloading cached
# executables is unsound — a warm process segfaults or loads a
# silently wrong program (reproduced at the PRE-pipeline HEAD, so it
# is a fabric landmine, not a pipeline regression; PERF.md §7 records
# the experiment, and northstar/bench now platform-gate the same
# wiring).  Every runner here compiles cold, which is also what the
# AOT assertions need.


def _tampered_reports(m):
    """10 reports over 3-bit values [0 x3, 5 x3, 3, 1, 6 x2]; report 4
    (a 5) fails the eval proof, report 7 (the 1) fails the weight
    check — rejection attribution must survive pipelining."""
    meas = [((bool(v >> 2 & 1), bool(v >> 1 & 1), bool(v & 1)), True)
            for v in [0, 0, 0, 5, 5, 5, 3, 1, 6, 6]]
    reports = get_reports_from_measurements(m, CTX, meas)
    (nonce, ps, shares) = reports[4]
    (key, proof, seed, part) = shares[0]
    reports[4] = (nonce, ps, [
        (bytes([key[0] ^ 1]) + key[1:], proof, seed, part), shares[1]])
    (nonce, ps, shares) = reports[7]
    (key, proof, seed, part) = shares[0]
    bad_proof = [proof[0] + m.field(1)] + proof[1:]
    reports[7] = (nonce, ps, [(key, bad_proof, seed, part), shares[1]])
    return reports


def _clean_reports(m):
    """7 reports: 0 x3, 7 x3, 3 x1 — hitters {000, 111} at
    threshold 2, frontier steady at 4 from level 1 (one surviving
    child per parent: the AOT predictor's fixed point)."""
    meas = [(m.vidpf.test_index_from_int(v, 3), True)
            for v in (0, 0, 0, 7, 7, 7, 3)]
    return get_reports_from_measurements(m, CTX, meas)


def _ckpt_arrays(blob: bytes) -> dict:
    return dict(np.load(io.BytesIO(blob), allow_pickle=False))


def _assert_state_equal(blob_a: bytes, blob_b: bytes) -> None:
    """Checkpoint state equality, array for array.  (The raw npz
    container embeds zip-entry mtimes, so literal blob equality is
    time-of-day-dependent; the arrays ARE the state.)"""
    (a, b) = (_ckpt_arrays(blob_a), _ckpt_arrays(blob_b))
    assert sorted(a) == sorted(b)
    for k in a:
        assert np.array_equal(a[k], b[k]), f"checkpoint array {k}"


def _run_all(run) -> None:
    while run.step():
        pass


def _counters(metrics) -> list:
    return [(m.level, m.accepted, m.rejected_eval_proof,
             m.rejected_weight_check, m.rejected_joint_rand,
             m.rejected_fallback, m.xof_fallbacks, m.node_evals,
             m.padded_width) for m in metrics]


# -- executor + cache host-level units (no device work) --------------


def test_run_chunks_ordering():
    log = []

    def stage(i):
        log.append(("stage", i))
        return (i * 10, {"upload_ms": 1.0})

    def collect(i, handle):
        assert handle == i * 10
        log.append(("collect", i))
        return {"host_ms": 1.0}

    (tl, _wall) = pipeline.run_chunks(3, stage, collect,
                                      pipelined=False)
    assert log == [("stage", 0), ("collect", 0), ("stage", 1),
                   ("collect", 1), ("stage", 2), ("collect", 2)]
    assert [rec["host_syncs"] for rec in tl] == [1, 1, 1]

    log.clear()
    (tl, _wall) = pipeline.run_chunks(3, stage, collect,
                                      pipelined=True)
    # Double buffering: chunk i+1 stages BEFORE chunk i collects.
    assert log == [("stage", 0), ("stage", 1), ("collect", 0),
                   ("stage", 2), ("collect", 1), ("collect", 2)]
    for i in range(2):
        assert tl[i + 1]["stage_start_ms"] < tl[i]["collect_start_ms"]
    assert all(rec["phases"]["upload_ms"] == 1.0 for rec in tl)


def test_overlap_efficiency_math():
    tl = [{"phases": {"upload_ms": 10.0, "compute_wait_ms": 10.0}},
          {"phases": {"upload_ms": 10.0, "compute_wait_ms": 10.0}}]
    assert pipeline.overlap_efficiency(tl, 40.0) == 0.0  # serial
    assert pipeline.overlap_efficiency(tl, 20.0) == 0.5  # half hidden
    assert pipeline.overlap_efficiency(tl, 0.0) == 0.0


class _FakeLowered:
    def __init__(self, tag, delay=0.0, fail=False):
        (self.tag, self.delay, self.fail) = (tag, delay, fail)

    def compile(self):
        if self.delay:
            time.sleep(self.delay)
        if self.fail:
            raise RuntimeError("boom")
        return ("compiled", self.tag)


def test_program_cache_inline_warm_and_errors():
    cache = pipeline.ProgramCache()
    (prog, wait) = cache.get("k1", lambda: _FakeLowered(1))
    assert prog == ("compiled", 1) and wait > 0.0
    (prog, wait) = cache.get("k1", lambda: _FakeLowered(99))
    assert prog == ("compiled", 1) and wait == 0.0  # cached: no wait
    assert cache.stats["inline_compiles"] == 1

    assert cache.warm("k2", lambda: _FakeLowered(2, delay=0.01)) > 0.0
    assert cache.warm("k2", lambda: _FakeLowered(3)) == 0.0  # dedup
    (prog, wait) = cache.get("k2", lambda: _FakeLowered(4))
    assert prog == ("compiled", 2) and wait == 0.0  # warmed: free
    assert cache.stats["warm_compiles"] == 1

    # A failing warm is counted, never raised; the round that needs
    # the key compiles inline afterwards.
    cache.warm("k3", lambda: _FakeLowered(5, fail=True))
    assert cache.stats["warm_errors"] == 1
    (prog, _wait) = cache.get("k3", lambda: _FakeLowered(6))
    assert prog == ("compiled", 6)
    assert not cache.contains("k4")


def test_predicted_next_plans_candidates():
    from mastic_tpu.backend.incremental import RoundPlan

    bits = 6
    # Level-1 frontier: both children of both root children.
    prefixes = [(a, b) for a in (False, True) for b in (False, True)]
    layouts = [[(False,), (True,)]]
    plan = RoundPlan(tuple(prefixes), 1, bits, 8, layouts)
    nxt = pipeline.predicted_next_plans(
        plan.prefixes, 1, bits, 8, layouts + [plan.layout_new])
    keys = {pipeline.plan_shape_key(p) for p in nxt}
    assert len(nxt) == len(keys)  # deduplicated by shape
    # Growth candidate: all 4 survive -> 8 prefixes (out bucket 8);
    # steady candidate: one child per parent -> 4 (out bucket 4).
    assert {k[3] for k in keys} == {4, 8}
    # Last level: nothing to predict.
    assert pipeline.predicted_next_plans(
        plan.prefixes, bits - 1, bits, 8, layouts) == []
    # Candidates that would overflow the padded width are skipped
    # (the grow round compiles inline by design): width 4 holds only
    # 2 ancestor slots, the growth candidate needs 4.
    nxt_small = pipeline.predicted_next_plans(
        plan.prefixes, 1, bits, 4, layouts + [plan.layout_new])
    assert {pipeline.plan_shape_key(p)[3] for p in nxt_small} <= {4}


# -- bit-identity: pipelined vs serial across chunk layouts ----------


@pytest.mark.parametrize("chunk_size,num_chunks", [
    # single chunk (serial fallback named "single-chunk")
    pytest.param(12, 1, marks=pytest.mark.slow),
    # two chunks, no tail padding
    pytest.param(5, 2, marks=pytest.mark.slow),
    (4, 3),    # three chunks, partial tail (2 live of 4 padded)
], ids=["1chunk", "2chunk", "3chunk-tail"])
def test_pipelined_matches_serial(monkeypatch, chunk_size,
                                  num_chunks) -> None:
    m = MasticCount(3)
    reports = _tampered_reports(m)
    vk = gen_rand(m.VERIFY_KEY_SIZE)
    thresholds = {"default": 2}

    def full_run(lever):
        monkeypatch.setenv("MASTIC_PIPELINE", lever)
        run = HeavyHittersRun(m, CTX, thresholds, reports,
                              verify_key=vk, chunk_size=chunk_size)
        _run_all(run)
        return run

    serial = full_run("0")
    piped = full_run("1")
    assert serial.store.num_chunks == num_chunks

    # Same verdicts, counters and aggregates at every level; the
    # carried state (what every later round derives from) is
    # bit-identical in the checkpoint arrays.
    assert _counters(serial.metrics) == _counters(piped.metrics)
    assert serial.result() == piped.result()
    assert serial.result()  # the honest hitters survive
    _assert_state_equal(serial.to_bytes(), piped.to_bytes())

    # Modes are honest: overlap only with >1 chunk and lever on.
    ser_pl = serial.metrics[0].extra["pipeline"]
    pip_pl = piped.metrics[0].extra["pipeline"]
    assert ser_pl["mode"] == "serial"
    assert ser_pl["fallback"] == "lever-off"
    if num_chunks == 1:
        assert pip_pl == dict(pip_pl, mode="serial",
                              fallback="single-chunk")
    else:
        assert pip_pl["mode"] == "pipelined"
        assert pip_pl["fallback"] is None
    # Rejection attribution survived chunking + pipelining.
    assert piped.metrics[0].rejected_eval_proof == 1
    assert piped.metrics[0].rejected_weight_check == 1

    if num_chunks == 3:
        # Satellite: tail-chunk rate honesty — the tail computes
        # chunk_size padded lanes but holds only 2 live reports, so
        # the live rate must be stamped alongside the padded one.
        for run in (serial, piped):
            chunks = run.metrics[-1].extra["chunks"]
            tail = chunks[-1]
            assert tail["reports"] == 2
            assert tail["node_evals_per_sec"] == pytest.approx(
                tail["node_evals_per_sec_padded"] * 2 / chunk_size,
                rel=0.01)
            full = chunks[0]
            assert full["node_evals_per_sec"] == \
                full["node_evals_per_sec_padded"]


@pytest.mark.slow
def test_level0_agg_shares_identical(monkeypatch) -> None:
    """The round's RETURNED aggregate (unsharded from the accumulated
    agg shares) is identical serial vs pipelined — the direct
    agg-share probe on top of the carried-state identity above."""
    m = MasticCount(3)
    reports = _tampered_reports(m)
    vk = gen_rand(m.VERIFY_KEY_SIZE)
    param = (0, ((False,), (True,)), True)

    def level0(lever):
        monkeypatch.setenv("MASTIC_PIPELINE", lever)
        run = HeavyHittersRun(m, CTX, {"default": 2}, reports,
                              verify_key=vk, chunk_size=4)
        return run.runner.round(param)

    assert level0("0") == level0("1")


# -- measured overlap: injected store latency ------------------------


def test_overlap_timeline_under_store_latency(monkeypatch) -> None:
    """With injected store latency, the pipelined round stages chunk
    i+1 while chunk i's dispatched work is still in flight (its
    collect has not begun): upload overlaps compute.  Serial mode
    shows strict ordering.  Either way each chunk pays exactly one
    blocking host sync."""
    m = MasticCount(3)
    reports = _tampered_reports(m)
    vk = gen_rand(m.VERIFY_KEY_SIZE)

    real_slice = HostReportStore.host_slice

    def slow_slice(self, x, i):
        time.sleep(0.004)  # ~10 arrays/chunk -> ~40ms staging
        return real_slice(self, x, i)

    def one_round(lever):
        monkeypatch.setenv("MASTIC_PIPELINE", lever)
        run = HeavyHittersRun(m, CTX, {"default": 2}, reports,
                              verify_key=vk, chunk_size=4)
        monkeypatch.setattr(HostReportStore, "host_slice", slow_slice)
        run.step()
        monkeypatch.setattr(HostReportStore, "host_slice", real_slice)
        return run.metrics[0].extra

    piped = one_round("1")
    tl = piped["chunks"]
    assert piped["pipeline"]["mode"] == "pipelined"
    for i in range(len(tl) - 1):
        # Chunk i+1's staging began (and finished) before chunk i's
        # collect — i.e. while chunk i's async-dispatched round was
        # still computing.
        assert tl[i + 1]["stage_start_ms"] < tl[i]["collect_start_ms"]
        assert tl[i + 1]["stage_end_ms"] <= tl[i]["collect_end_ms"]
    assert all(rec["host_syncs"] == 1 for rec in tl)
    phases = tl[0]["phases"]
    assert set(phases) >= {"upload_ms", "dispatch_ms", "compile_ms",
                           "compute_wait_ms", "download_ms",
                           "host_ms"}
    assert phases["upload_ms"] >= 20.0  # the injected latency landed

    serial = one_round("0")
    tl = serial["chunks"]
    assert serial["pipeline"]["mode"] == "serial"
    for i in range(len(tl) - 1):
        assert tl[i + 1]["stage_start_ms"] >= tl[i]["collect_end_ms"]
    assert all(rec["host_syncs"] == 1 for rec in tl)


# -- ahead-of-time bucket compilation --------------------------------


def test_aot_predicted_buckets_compile_free(monkeypatch) -> None:
    """Steady-state frontier: after the first round, every round's
    programs were compiled ahead of time from the predicted frontier
    trajectory (while the previous round's device work was in
    flight) — zero inline compile wait, measured via the timeline's
    compile field on a cold per-runner cache."""
    monkeypatch.setenv("MASTIC_PIPELINE", "1")
    m = MasticCount(3)
    run = HeavyHittersRun(m, CTX, {"default": 2}, _clean_reports(m),
                          verify_key=gen_rand(m.VERIFY_KEY_SIZE),
                          chunk_size=4)
    _run_all(run)
    assert sorted(run.result()) == sorted(
        [m.vidpf.test_index_from_int(v, 3) for v in (0, 7)])

    first = run.metrics[0].extra["pipeline"]
    assert first["compile_inline_ms"] > 0.0  # cold start pays once
    for mx in run.metrics[1:]:
        pl = mx.extra["pipeline"]
        assert pl["aot"]["predicted"], f"level {mx.level} unpredicted"
        assert pl["compile_inline_ms"] == 0.0, \
            f"level {mx.level} paid inline compile"
        assert pl["aot"]["compile_wait_ms"] == 0.0
    stats = run.runner.programs.stats
    assert stats["warm_compiles"] > 0
    assert stats["warm_errors"] == 0
    # The predictor warmed at most its two candidates per round.
    assert stats["inline_compiles"] + stats["warm_compiles"] <= \
        2 + 4 * len(run.metrics)


@pytest.mark.slow
def test_aot_mispredict_compiles_inline_correctly(
        monkeypatch) -> None:
    """A frontier that outgrows the padded width breaks the
    prediction (grow candidates are skipped by design): the grow
    round pays its compile inline and still produces the correct
    result — byte-equal to the serial reference."""
    m = MasticCount(5)
    meas = [(m.vidpf.test_index_from_int(v * 4, 5), True)
            for v in range(8)]
    reports = get_reports_from_measurements(m, CTX, meas)
    vk = gen_rand(m.VERIFY_KEY_SIZE)

    def full_run(lever):
        monkeypatch.setenv("MASTIC_PIPELINE", lever)
        run = HeavyHittersRun(m, CTX, {"default": 1}, reports,
                              verify_key=vk, chunk_size=4)
        _run_all(run)
        return run

    piped = full_run("1")
    assert piped.runner.width == 16  # the growth happened
    grow_round = piped.metrics[3].extra["pipeline"]
    assert not grow_round["aot"]["predicted"]
    assert grow_round["compile_inline_ms"] > 0.0
    assert sorted(piped.result()) == sorted(
        m.vidpf.test_index_from_int(v * 4, 5) for v in range(8))

    serial = full_run("0")
    assert _counters(serial.metrics) == _counters(piped.metrics)
    _assert_state_equal(serial.to_bytes(), piped.to_bytes())


# -- envelope honesty + budget fallback ------------------------------


def test_envelope_pipeline_residency_fields() -> None:
    m = MasticCount(3)
    bm = BatchedMastic(m)
    reports = _clean_reports(m)
    run = HeavyHittersRun(m, CTX, {"default": 2}, reports,
                          verify_key=gen_rand(m.VERIFY_KEY_SIZE),
                          chunk_size=4)
    env = memory_envelope(bm, 4, run.runner.width, len(reports))
    mem = run.runner.memory_accounting()
    # The serial parity (locked in test_chunked) extends to the
    # pipelined term: exactly two chunks' resident state, plus one
    # chunk's worst-case binder staging.
    assert env["pipeline_chunks_in_flight"] == \
        PIPELINE_CHUNKS_IN_FLIGHT == 2
    assert env["device_bytes_per_chunk"] == \
        mem["device_bytes_per_chunk"]
    assert env["device_bytes_per_chunk_pipelined"] == \
        2 * mem["device_bytes_per_chunk"]
    assert env["device_peak_bytes_per_chunk_pipelined"] == \
        (2 * env["device_bytes_per_chunk"]
         + env["device_peak_bytes_per_chunk"]
         - env["device_bytes_per_chunk"])
    assert 0 < env["max_pipelined_chunk_size_at_width"] \
        <= env["max_chunk_size_at_width"] // 2 + 1
    # The shared cost model prices N chunks in flight linearly in the
    # resident term and once in staging.
    one = round_peak_bytes(bm, 2, 1, 4, 1000)
    two = round_peak_bytes(bm, 2, 1, 4, 1000, chunks_in_flight=2)
    assert two - one == 1000


@pytest.mark.slow
def test_budget_fallback_to_serial(monkeypatch) -> None:
    """A budget that admits one chunk in flight but not two: the
    executor degrades to serial, NAMES the fallback in metrics, and
    the run stays correct."""
    monkeypatch.setenv("MASTIC_PIPELINE", "1")
    m = MasticCount(3)
    bm = BatchedMastic(m)
    reports = _clean_reports(m)
    run = HeavyHittersRun(m, CTX, {"default": 2}, reports,
                          verify_key=gen_rand(m.VERIFY_KEY_SIZE),
                          chunk_size=4)
    resident = run.runner.memory_accounting()["device_bytes_per_chunk"]
    # Level-0 buckets: onehot 2, payload 1 (no internal nodes yet).
    serial_peak = round_peak_bytes(bm, 2, 1, 4, resident)
    pipe_peak = round_peak_bytes(bm, 2, 1, 4, resident,
                                 chunks_in_flight=2)
    assert pipe_peak > serial_peak
    monkeypatch.setenv("MASTIC_DEVICE_BUDGET_BYTES",
                       str((serial_peak + pipe_peak) // 2))
    run.step()
    pl = run.metrics[0].extra["pipeline"]
    assert pl["mode"] == "serial"
    assert pl["fallback"] == "device-budget"
    monkeypatch.delenv("MASTIC_DEVICE_BUDGET_BYTES")
    _run_all(run)
    assert run.metrics[1].extra["pipeline"]["mode"] == "pipelined"
    assert sorted(run.result()) == sorted(
        [m.vidpf.test_index_from_int(v, 3) for v in (0, 7)])


# -- program-cache shape keying: grow then weight check --------------


@pytest.mark.slow
def test_grow_then_weight_check(monkeypatch) -> None:
    """Round programs are keyed by the shapes they close over, so a
    width growth BEFORE a weight-check round (the attribute-metrics
    shape: one weight-checked aggregation at an arbitrary level, or a
    checkpoint restored at a grown width) runs correctly — the
    r5..r8 `_grow` cleared `_eval_fn`/`_agg_fn` but not `_wc_fns`,
    which was only safe because the wc program's input shapes are
    width-independent.  Locked here: grow to width 16, then run the
    weight-check round and the rest of the collection bit-identically
    to the ungrown reference."""
    monkeypatch.setenv("MASTIC_PIPELINE", "1")
    m = MasticCount(3)
    reports = _tampered_reports(m)
    vk = gen_rand(m.VERIFY_KEY_SIZE)

    ref = HeavyHittersRun(m, CTX, {"default": 2}, reports,
                          verify_key=vk, chunk_size=4)
    _run_all(ref)

    grown = HeavyHittersRun(m, CTX, {"default": 2}, reports,
                            verify_key=vk, chunk_size=4)
    grown.runner._grow(16)
    assert grown.runner.width == 16
    _run_all(grown)

    assert grown.result() == ref.result()
    for (a, b) in zip(ref.metrics, grown.metrics):
        assert (a.accepted, a.rejected_eval_proof,
                a.rejected_weight_check, a.rejected_joint_rand) == \
            (b.accepted, b.rejected_eval_proof,
             b.rejected_weight_check, b.rejected_joint_rand)
    # The weight check fired at the grown width and still attributed.
    assert grown.metrics[0].rejected_weight_check == 1
    assert grown.metrics[0].padded_width == 16
    # Every compiled program key carries the width it closed over
    # (key layout: ("eval", rows, mesh_shards, width, buckets...) —
    # r10 added the mesh shape at slot 2; no mesh here, so 0).
    eval_keys = [k for k in grown.runner.programs._programs
                 if k[0] == "eval"]
    assert eval_keys and all(k[2] == 0 and k[3] == 16
                             for k in eval_keys)


# -- composition: checkpoint kill-resume with faults armed -----------


@pytest.mark.slow
def test_kill_resume_pipelined_with_faults_armed(monkeypatch) -> None:
    """A pipelined run killed after a checkpoint resumes (PR 3-style
    snapshot/replay) bit-identically to an uninterrupted run, with
    the `MASTIC_FAULTS` lever armed throughout — pipelining composes
    with the fault-injection machinery instead of fighting it (the
    chunked runner is in-process, so the session-layer rules are
    inert here; the slow tier runs the process-separated session
    under the pipeline lever)."""
    monkeypatch.setenv("MASTIC_PIPELINE", "1")
    monkeypatch.setenv("MASTIC_FAULTS",
                       "kill:party=helper:step=round_start")
    m = MasticCount(3)
    reports = _tampered_reports(m)
    vk = gen_rand(m.VERIFY_KEY_SIZE)
    thresholds = {"default": 2}

    ref = HeavyHittersRun(m, CTX, thresholds, reports, verify_key=vk,
                          chunk_size=4)
    _run_all(ref)

    victim = HeavyHittersRun(m, CTX, thresholds, reports,
                             verify_key=vk, chunk_size=4)
    victim.step()
    blob = victim.to_bytes()
    del victim  # the "kill": only the snapshot survives

    resumed = HeavyHittersRun.from_bytes(m, CTX, thresholds, reports,
                                         vk, blob)
    assert resumed.level == 1
    assert resumed.runner.store.num_chunks == 3
    _run_all(resumed)
    assert resumed.result() == ref.result()
    _assert_state_equal(ref.to_bytes(), resumed.to_bytes())


# -- resident runner: deferred-sync round ----------------------------


@pytest.mark.slow
def test_resident_deferred_round_timeline(monkeypatch) -> None:
    monkeypatch.setenv("MASTIC_PIPELINE", "1")
    m = MasticCount(3)
    reports = _tampered_reports(m)
    run = HeavyHittersRun(m, CTX, {"default": 2}, reports,
                          verify_key=gen_rand(m.VERIFY_KEY_SIZE))
    _run_all(run)
    assert sorted(run.result()) == sorted(
        m.vidpf.test_index_from_int(v, 3) for v in (0, 5, 6))
    assert run.metrics[0].rejected_eval_proof == 1
    assert run.metrics[0].rejected_weight_check == 1
    for mx in run.metrics:
        pl = mx.extra["pipeline"]
        assert pl["mode"] == "resident-deferred"
        assert pl["host_syncs"] == 1
        assert set(pl["phases"]) == {"upload_ms", "compile_ms",
                                     "dispatch_ms", "warm_ms",
                                     "compute_wait_ms",
                                     "download_ms", "host_ms"}
    # AOT warming applies to the resident loop too.
    assert run.runner.programs.stats["warm_compiles"] > 0


# -- the from-root chunked attribute round ---------------------------


@pytest.mark.slow
def test_attribute_round_chunked_pipelined(monkeypatch) -> None:
    from mastic_tpu.drivers.attribute_metrics import (
        aggregate_by_attribute)

    m = MasticCount(8)
    attrs = ["checkout", "landing"]  # hash-distinct at BITS=8
    from mastic_tpu.drivers.attribute_metrics import hash_attribute
    meas = [(hash_attribute(m, "checkout"), True)] * 3 + \
        [(hash_attribute(m, "landing"), True)]
    reports = get_reports_from_measurements(m, CTX, meas)
    vk = gen_rand(m.VERIFY_KEY_SIZE)

    whole = aggregate_by_attribute(m, CTX, attrs, reports,
                                   verify_key=vk)
    monkeypatch.setenv("MASTIC_PIPELINE", "1")
    out_p: list = []
    piped = aggregate_by_attribute(m, CTX, attrs, reports,
                                   verify_key=vk, chunk_size=2,
                                   metrics_out=out_p)
    monkeypatch.setenv("MASTIC_PIPELINE", "0")
    out_s: list = []
    serial = aggregate_by_attribute(m, CTX, attrs, reports,
                                    verify_key=vk, chunk_size=2,
                                    metrics_out=out_s)
    assert whole == piped == serial == \
        [("checkout", 3), ("landing", 1)]
    assert out_p[0].extra["pipeline"]["mode"] == "pipelined"
    assert out_s[0].extra["pipeline"]["mode"] == "serial"
    tl = out_p[0].extra["chunks"]
    assert len(tl) == 2 and all(r["host_syncs"] == 1 for r in tl)
    assert tl[1]["stage_start_ms"] < tl[0]["collect_start_ms"]


# -- slow tier: the process-separated session under the lever --------


@pytest.mark.slow
def test_session_kill_resume_under_pipeline_lever(monkeypatch):
    """PR 3's headline kill-and-resume (process-separated parties,
    respawn + replay) runs bit-identically with the pipeline lever
    pinned on — the env var reaches the spawned parties, proving the
    two levers compose end to end."""
    from mastic_tpu.drivers.parties import AggregationSession
    from mastic_tpu.drivers.session import SessionConfig

    monkeypatch.setenv("MASTIC_PIPELINE", "1")
    cfg = SessionConfig(connect_timeout=30.0, exchange_timeout=240.0,
                        ack_timeout=60.0, round_deadline=600.0,
                        shutdown_timeout=5.0, retries=1, backoff=0.2)
    m = MasticCount(2)
    vk = gen_rand(m.VERIFY_KEY_SIZE)
    spec = {"class": "MasticCount", "args": [2]}
    param = (0, ((False,), (True,)), True)
    reports = get_reports_from_measurements(
        m, CTX, [((False, True), True), ((True, False), True)])

    sess0 = AggregationSession(m, spec, CTX, vk, config=cfg)
    try:
        sess0.upload(reports)
        (r0, a0, s0) = sess0.round(param)
    finally:
        sess0.close()

    sess1 = AggregationSession(
        m, spec, CTX, vk, config=cfg,
        faults_spec="kill:party=helper:step=round_start")
    try:
        sess1.upload(reports)
        (r1, a1, s1) = sess1.round(param)
    finally:
        sess1.close()
    assert sess1.counters["respawns"] == 1
    assert (r1, list(a1), s1) == (r0, list(a0), s0)
