"""Structured span tracer (ISSUE 7 tentpole, part 1).

A span is one timed operation with attributes and point-in-time
events; spans nest through an explicit parent link, so a trace of a
service epoch reconstructs the epoch -> round -> chunk hierarchy that
the scattered `extra` dicts could never express.  Design constraints
the runtime imposes:

* **cheap when idle** — starting/ending a span is a few dict ops and
  one `time.perf_counter()` pair; no I/O unless `MASTIC_TRACE_FILE`
  is set.  The measured overhead on the incremental-round bench is
  <1% (PERF.md §10), so tracing is always on;
* **bounded memory** — finished spans land in a ring buffer
  (default 4096); eviction is counted (`dropped()`), never silent;
* **thread-aware** — the active-span stack is thread-local (the
  statusz server thread must not adopt the scheduler's spans), while
  the ring and the JSONL sink are lock-protected so any thread may
  finish a span;
* **crash-friendly JSONL** — with `MASTIC_TRACE_FILE=path` every
  finished span appends one JSON line (O_APPEND, single write), so a
  killed process loses at most the span in flight and two processes
  sharing the file interleave whole lines.

Span records (`Span.as_dict`, the JSONL line) carry:

    name, span_id, parent_id, t_start_ms, duration_ms, attrs, events

where `t_start_ms` is milliseconds on the tracer's monotonic epoch
(comparable within one process) and each event is
`{"name", "t_ms", "attrs"}`.  `read_jsonl` / `build_tree` reconstruct
the hierarchy for tests and offline diffing — bench runs and the live
service emit the same schema, so their traces diff directly.
"""

import json
import os
import threading
import time
from collections import deque
from typing import Iterator, Optional

# Ring capacity: at the north-star shape one epoch is ~256 rounds of
# ~a few chunks, so 4096 finished spans hold several epochs.
DEFAULT_CAPACITY = 4096


class Span:
    """One timed operation.  Created by Tracer.span / start_span;
    by convention mutated only by its owning thread until `end` —
    and since ISSUE 11 (the upload front put server threads next to
    the scheduler everywhere) the convention is enforced: every
    post-construction mutation happens under the span's own lock, so
    a mis-shared span degrades to racy-but-sound instead of torn."""

    __slots__ = ("name", "span_id", "parent_id", "t_start_ms",
                 "duration_ms", "attrs", "events", "_tracer",
                 "_lock")

    def __init__(self, name: str, span_id: int,
                 parent_id: Optional[int], t_start_ms: float,
                 attrs: dict, tracer: "Tracer",
                 duration_ms: Optional[float] = None):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.t_start_ms = t_start_ms
        # Pre-set only by Tracer.record_span (the already-finished
        # single-call form); live spans get it at end_span.
        self.duration_ms: Optional[float] = duration_ms
        self.attrs = attrs
        self.events: list = []
        self._tracer = tracer
        self._lock = threading.Lock()

    def set(self, **attrs) -> "Span":
        with self._lock:
            self.attrs.update(attrs)
        return self

    def set_default(self, name: str, value) -> None:
        """`attrs.setdefault`, under the span lock (the error-attr
        stamp the drivers' collect paths use)."""
        with self._lock:
            self.attrs.setdefault(name, value)

    def event(self, name: str, **attrs) -> None:
        t_ms = round(self._tracer.now_ms(), 3)
        with self._lock:
            self.events.append({
                "name": name,
                "t_ms": t_ms,
                "attrs": attrs,
            })

    def as_dict(self) -> dict:
        return {
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "t_start_ms": round(self.t_start_ms, 3),
            "duration_ms": (None if self.duration_ms is None
                            else round(self.duration_ms, 3)),
            "attrs": self.attrs,
            "events": self.events,
        }


class _SpanContext:
    """Context-manager wrapper so `with tracer.span(...) as sp:` both
    times the block and pops the thread-local stack on exit."""

    __slots__ = ("_tracer", "_span")

    def __init__(self, tracer: "Tracer", span: Span):
        self._tracer = tracer
        self._span = span

    def __enter__(self) -> Span:
        return self._span

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is not None:
            self._span.set_default("error", exc_type.__name__)
        self._tracer.end_span(self._span)


class _ParentContext:
    """Push an ALREADY-OPEN span as the current parent without timing
    it (the service scheduler holds an epoch span open across many
    `step()` quanta; each quantum's round span must still parent to
    it)."""

    __slots__ = ("_tracer", "_span")

    def __init__(self, tracer: "Tracer", span: Optional[Span]):
        self._tracer = tracer
        self._span = span

    def __enter__(self) -> Optional[Span]:
        if self._span is not None:
            self._tracer._stack().append(self._span)
        return self._span

    def __exit__(self, exc_type, exc, tb) -> None:
        if self._span is None:
            return
        stack = self._tracer._stack()
        if stack and stack[-1] is self._span:
            stack.pop()


class Tracer:
    """The process-wide span recorder (module singleton via
    `get_tracer`; tests build private instances)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 trace_file: Optional[str] = None):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=capacity)
        self._dropped = 0
        self._finished = 0
        self._seq = 0
        self._local = threading.local()
        self._epoch = time.perf_counter()
        # The JSONL sink: explicit arg wins; otherwise the env lever,
        # read once at construction (configure() rebuilds the
        # singleton, so a long-lived process CAN be re-aimed).
        self.trace_file = (trace_file
                           if trace_file is not None
                           else os.environ.get("MASTIC_TRACE_FILE")
                           or None)

    # -- clock / stack plumbing ------------------------------------

    def now_ms(self) -> float:
        return (time.perf_counter() - self._epoch) * 1e3

    def _stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = []
            self._local.stack = stack
        return stack

    def current(self) -> Optional[Span]:
        stack = self._stack()
        return stack[-1] if stack else None

    # -- span lifecycle --------------------------------------------

    def start_span(self, name: str, parent: Optional[Span] = None,
                   **attrs) -> Span:
        """Open a span and make it the thread's current parent.  The
        caller MUST pass it to `end_span` (or use `span()` for the
        with-block form)."""
        with self._lock:
            self._seq += 1
            span_id = self._seq
        if parent is None:
            parent = self.current()
        sp = Span(name, span_id,
                  parent.span_id if parent is not None else None,
                  self.now_ms(), dict(attrs), self)
        self._stack().append(sp)
        return sp

    def start_detached_span(self, name: str,
                            parent: Optional[Span] = None,
                            **attrs) -> Span:
        """Open a span WITHOUT making it the thread's current parent
        — for long-lived spans that interleave (the service holds one
        epoch span per tenant open across round-robined quanta; each
        quantum adopts the right one via `use_parent`)."""
        sp = self.start_span(name, parent, **attrs)
        stack = self._stack()
        if stack and stack[-1] is sp:
            stack.pop()
        return sp

    def record_span(self, name: str, duration_ms: float = 0.0,
                    parent: Optional[Span] = None, **attrs) -> Span:
        """One ALREADY-FINISHED span in a single call — the form for
        server/handler threads (the upload front's `net.request`,
        ISSUE 11): every field lands in the constructor, so there is
        no post-construction mutation for another thread to race
        (the CC001 ownership story, by construction instead of by
        promise), and the ring/sink append is the same lock-guarded
        `_record` every span takes.  Never touches the thread-local
        stack."""
        with self._lock:
            self._seq += 1
            span_id = self._seq
        sp = Span(name, span_id,
                  parent.span_id if parent is not None else None,
                  self.now_ms() - duration_ms, dict(attrs), self,
                  duration_ms=duration_ms)
        self._record(sp)
        return sp

    def end_span(self, span: Span) -> None:
        # Under the tracer lock: ending is the only cross-thread-
        # visible mutation a span ever gets (record_span's are all
        # constructor-time), and the ring append below re-takes the
        # same lock anyway.
        with self._lock:
            span.duration_ms = self.now_ms() - span.t_start_ms
        stack = self._stack()
        if stack and stack[-1] is span:
            stack.pop()
        elif span in stack:
            # Ended out of order (nested spans closed non-LIFO):
            # remove wherever it sits, keep going.  Detached spans
            # (start_detached_span) are never on the stack at all.
            stack.remove(span)
        self._record(span)

    def span(self, name: str, parent: Optional[Span] = None,
             **attrs) -> _SpanContext:
        """`with tracer.span("round", level=3) as sp:` — times the
        block, pops on exit, records an `error` attr on exception."""
        return _SpanContext(self, self.start_span(name, parent,
                                                  **attrs))

    def use_parent(self, span: Optional[Span]) -> _ParentContext:
        """Adopt an open span as the current parent for a block
        without re-timing it (see _ParentContext)."""
        return _ParentContext(self, span)

    def event(self, name: str, **attrs) -> None:
        """Attach a point-in-time event to the current span; with no
        span open, record a standalone zero-duration span so the
        event still reaches the ring and the JSONL sink (the session
        layer's retry events fire outside any span in the in-process
        fault tests)."""
        cur = self.current()
        if cur is not None:
            cur.event(name, **attrs)
            return
        # The marker rides the constructor (record_span discipline:
        # no post-construction span mutation off the owning thread).
        sp = self.start_span(name, standalone_event=True, **attrs)
        self.end_span(sp)

    # -- ring / sink -----------------------------------------------

    def _record(self, span: Span) -> None:
        line = None
        if self.trace_file:
            line = json.dumps(span.as_dict(),
                              separators=(",", ":")) + "\n"
        with self._lock:
            evicted = len(self._ring) == self._ring.maxlen
            if evicted:
                self._dropped += 1
            self._ring.append(span)
            self._finished += 1
        # Mirror into the registry so span volume / ring pressure is
        # scrapeable (imported here, not at module top, purely to
        # keep the two singletons independently replaceable in tests).
        from .registry import get_registry

        get_registry().counter("mastic_trace_spans_total").inc()
        if evicted:
            get_registry().counter(
                "mastic_trace_spans_dropped_total").inc()
        if line is not None:
            # One write per span, append mode: whole lines interleave
            # safely when party subprocesses share the file.
            with open(self.trace_file, "a") as f:
                f.write(line)

    def spans(self) -> list:
        """Finished spans currently in the ring (snapshot copy)."""
        with self._lock:
            return list(self._ring)

    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def finished(self) -> int:
        with self._lock:
            return self._finished

    def snapshot(self) -> dict:
        """JSON-able tracer state for /varz."""
        with self._lock:
            return {
                "capacity": self._ring.maxlen,
                "buffered": len(self._ring),
                "finished": self._finished,
                "dropped": self._dropped,
                "trace_file": self.trace_file,
            }


# -- the process-wide singleton ---------------------------------------

_tracer: Optional[Tracer] = None
_tracer_lock = threading.Lock()


def get_tracer() -> Tracer:
    global _tracer
    if _tracer is None:
        with _tracer_lock:
            if _tracer is None:
                _tracer = Tracer()
    return _tracer


def configure(capacity: int = DEFAULT_CAPACITY,
              trace_file: Optional[str] = None) -> Tracer:
    """Rebuild the singleton (tests, and long-lived processes that
    re-aim the JSONL sink).  Passing trace_file=None re-reads the
    MASTIC_TRACE_FILE lever."""
    global _tracer
    with _tracer_lock:
        _tracer = Tracer(capacity=capacity, trace_file=trace_file)
    return _tracer


def span(name: str, **attrs) -> _SpanContext:
    """Module-level convenience: `with trace.span("round", ...):`."""
    return get_tracer().span(name, **attrs)


def event(name: str, **attrs) -> None:
    get_tracer().event(name, **attrs)


# -- offline reconstruction (tests, trace diffing) ---------------------

def read_jsonl(path: str) -> list:
    """Parse a MASTIC_TRACE_FILE back into span dicts.  Truncated
    final lines (a crash mid-write) are skipped, not fatal."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                # A torn tail line from a killed writer is expected;
                # whole spans before it are intact.
                continue
    return out


def build_tree(spans: list) -> dict:
    """span_id -> list of child span dicts (roots under key None),
    children in start order — the hierarchy assertion helper."""
    tree: dict = {}
    for sp in sorted(spans, key=lambda s: s["t_start_ms"]):
        tree.setdefault(sp["parent_id"], []).append(sp)
    return tree


def walk(spans: list, name: str) -> Iterator[dict]:
    """Spans with a given name, in start order."""
    for sp in sorted(spans, key=lambda s: s["t_start_ms"]):
        if sp["name"] == name:
            yield sp
