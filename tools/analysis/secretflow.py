"""Pass 3 — secret-flow / constant-time taint on the scalar layer.

Scope: mastic_tpu/vidpf.py, mastic_tpu/mastic.py, mastic_tpu/aes.py,
mastic_tpu/xof.py — the scalar protocol layer, where the draft's
timing-hygiene expectations live (the batched backend replaces every
secret-dependent choice with a lane select by construction; the scalar
layer is where a branch on a seed-derived bit can actually leak).

Taint sources (intraprocedural, per function, to a fixpoint):
  * parameters whose name marks secret material (seed/key/rand/alpha/
    beta/measurement/input_share and _seed/_key/_rand suffixes);
  * attribute reads of secret node state (.seed, .ctrl, .w,
    .round_keys);
  * calls that produce XOF/PRG output or key material (.next,
    .next_vec, .derive_seed, .encrypt_block, .extend, .convert, .gen,
    .get_beta_share);
  * any value computed from a tainted value (calls with tainted
    arguments taint their result — int()/bool() casts preserve
    secrecy).

`len(x)` and `x is None` escape the taint: lengths and presence are
public protocol parameters in every construction here.

Rules:
  SF001  Python branch (`if`/`while`/ternary/`assert`) on a tainted
         value — secret-dependent control flow.
  SF002  subscript whose *index* is tainted — secret-dependent memory
         access (the classic table-lookup timing channel).

Known limitation (by design — the analysis is intraprocedural): taint
does not follow values into callees, so e.g. a variable-time helper
called *with* secret bytes is the call site's finding, not the
helper's.  The scalar layer is the differential oracle, not the
deployment path; real findings here are suppressed with that
justification rather than rewritten, and the backend twins are the
constant-time forms.
"""

import ast

from .core import Finding, call_name, for_target_taints, target_names

PASS_NAME = "secretflow"

RULES = {
    "SF001": "branch on secret-derived value",
    "SF002": "secret-dependent subscript index",
}

SCOPE_FILES = ("mastic_tpu/vidpf.py", "mastic_tpu/mastic.py",
               "mastic_tpu/aes.py", "mastic_tpu/xof.py")

_SECRET_PARAMS = {"seed", "seeds", "key", "keys", "rand", "alpha",
                  "alphas", "beta", "betas", "block", "measurement",
                  "measurements", "input_share", "input_shares",
                  "weight", "verify_key"}
_SECRET_SUFFIXES = ("_seed", "_seeds", "_key", "_keys", "_rand",
                    "_rands")
_SECRET_ATTRS = {"seed", "ctrl", "w", "round_keys"}
_SECRET_CALLS = {"next", "next_vec", "derive_seed", "expand_into_vec",
                 "encrypt_block", "extend", "convert", "gen",
                 "get_beta_share"}
_HOST_SAFE = {"len", "isinstance", "range", "enumerate", "hasattr",
              "type", "print", "sorted", "ValueError", "TypeError",
              "set"}


def in_scope(rel: str) -> bool:
    return rel in SCOPE_FILES


def _secret_param(name: str) -> bool:
    return name in _SECRET_PARAMS or name.endswith(_SECRET_SUFFIXES)


def _is_none_test(node: ast.Compare) -> bool:
    return (len(node.ops) == 1
            and isinstance(node.ops[0], (ast.Is, ast.IsNot)))


class _TaintAnalysis:
    def __init__(self, fn, info, findings, inherited=()):
        self.fn = fn
        self.info = info
        self.findings = findings
        self.tainted: set = set(inherited)
        args = fn.args
        for a in args.posonlyargs + args.args + args.kwonlyargs:
            if _secret_param(a.arg):
                self.tainted.add(a.arg)

    def is_tainted(self, node) -> bool:
        if node is None or isinstance(node, ast.Constant):
            return False
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            if node.attr in _SECRET_ATTRS:
                return True
            return self.is_tainted(node.value)
        if isinstance(node, ast.Subscript):
            return self.is_tainted(node.value)
        if isinstance(node, ast.Call):
            name = call_name(node)
            if isinstance(node.func, ast.Name) and name in _HOST_SAFE:
                return False
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _SECRET_CALLS:
                return True
            return (self.is_tainted(node.func)
                    or any(self.is_tainted(a) for a in node.args)
                    or any(self.is_tainted(k.value)
                           for k in node.keywords))
        if isinstance(node, ast.BinOp):
            return (self.is_tainted(node.left)
                    or self.is_tainted(node.right))
        if isinstance(node, ast.UnaryOp):
            return self.is_tainted(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(self.is_tainted(v) for v in node.values)
        if isinstance(node, ast.Compare):
            if _is_none_test(node):
                return False
            return (self.is_tainted(node.left)
                    or any(self.is_tainted(c) for c in node.comparators))
        if isinstance(node, ast.IfExp):
            return (self.is_tainted(node.body)
                    or self.is_tainted(node.orelse))
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self.is_tainted(e) for e in node.elts)
        if isinstance(node, ast.Starred):
            return self.is_tainted(node.value)
        if isinstance(node, (ast.ListComp, ast.GeneratorExp,
                             ast.SetComp)):
            return (self.is_tainted(node.elt)
                    or any(self.is_tainted(g.iter)
                           for g in node.generators))
        return False

    def _taint_target(self, target):
        self.tainted.update(target_names(target))

    def propagate(self):
        from .tracesafe import iter_scope

        for _ in range(10):
            before = len(self.tainted)
            for node in iter_scope(self.fn):
                if isinstance(node, ast.Assign):
                    if self.is_tainted(node.value):
                        for t in node.targets:
                            self._taint_target(t)
                elif isinstance(node, ast.AugAssign):
                    if self.is_tainted(node.value) \
                            or self.is_tainted(node.target):
                        self._taint_target(node.target)
                elif isinstance(node, ast.AnnAssign):
                    if node.value is not None \
                            and self.is_tainted(node.value):
                        self._taint_target(node.target)
                elif isinstance(node, ast.For):
                    self.tainted.update(for_target_taints(
                        node.target, node.iter, self.is_tainted))
                elif isinstance(node, (ast.ListComp, ast.GeneratorExp,
                                       ast.SetComp, ast.DictComp)):
                    for g in node.generators:
                        self.tainted.update(for_target_taints(
                            g.target, g.iter, self.is_tainted))
            if len(self.tainted) == before:
                break

    def report(self):
        from .tracesafe import iter_scope

        for node in iter_scope(self.fn):
            if isinstance(node, (ast.If, ast.While)) \
                    and self.is_tainted(node.test):
                self._flag("SF001", node,
                           "branch on secret-derived value "
                           f"'{ast.unparse(node.test)[:60]}'")
            elif isinstance(node, ast.IfExp) \
                    and self.is_tainted(node.test):
                self._flag("SF001", node,
                           "ternary on secret-derived value "
                           f"'{ast.unparse(node.test)[:60]}'")
            elif isinstance(node, ast.Assert) \
                    and self.is_tainted(node.test):
                self._flag("SF001", node,
                           "assert on secret-derived value")
            elif isinstance(node, ast.Subscript) \
                    and self.is_tainted(node.slice):
                self._flag("SF002", node,
                           "secret-dependent index "
                           f"'{ast.unparse(node)[:60]}'")
            # Comprehension iterating a secret container with a
            # secret-indexed lookup inside is caught by the Subscript
            # case (the loop target is tainted via propagate()).

    def _flag(self, rule, node, msg):
        self.findings.append(
            Finding(rule, self.info.rel, node.lineno, msg))


def _analyze(fn, info, findings, inherited=()):
    from .tracesafe import iter_scope

    ta = _TaintAnalysis(fn, info, findings, inherited)
    ta.propagate()
    ta.report()
    for node in iter_scope(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _analyze(node, info, findings, set(ta.tainted))


def check(info) -> list:
    findings: list = []

    def visit(body):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _analyze(node, info, findings)
            elif isinstance(node, ast.ClassDef):
                visit(node.body)

    visit(info.tree.body)
    seen = set()
    out = []
    for f in findings:
        if f.key() in seen:
            continue
        seen.add(f.key())
        out.append(f)
    return out
