"""Known-good twin of rb004_net_bad: every front buffer carries a
capacity bound, and the accept loop sheds at the bound (the
net/admission.py LRU-evicted bucket table pattern)."""
import collections
import queue


def make_front_state(bound: int):
    buckets = queue.Queue(maxsize=bound)
    pending_bodies = collections.deque(maxlen=bound)
    return (buckets, pending_bodies)


def accept_loop(listener, pending_bodies, bound: int):
    while True:
        if len(pending_bodies) >= bound:
            break
        pending_bodies.append(listener.take())
