"""Continuous-ingest collector service: admission control,
backpressure, and supervised multi-tenant epochs (ROADMAP open item 1).

Every driver below this layer runs one offline batch; production
Mastic is a *stream* of uploads hitting a long-lived collector that
must stay up through malformed reports, slow tenants, overload, and
process crashes.  This module is that collector:

* **paged report buffers** — admitted uploads append to fixed-size
  pages (`ReportPage`; the ragged tail page seals at epoch cut), so
  admission is O(1) per upload and an epoch's report set is a list of
  immutable pages whose integrity is digest-checked before any page
  feeds a round (the PAPERS.md "Ragged Paged Attention" shape:
  fixed-size pages, ragged tails, admission while rounds are in
  flight);

* **admission control** — every upload blob is decode-validated at
  the door against BOTH parties' views; a malformed blob quarantines
  with the r8 reason codes (`drivers/parties.REASON_*`), and a tenant
  whose quarantine count passes its limit is suspended (its later
  uploads shed with reason ``tenant-quarantined``) so one abusive
  tenant cannot starve the rest;

* **backpressure, never silent** — per-tenant buffered reports are
  bounded (`MASTIC_SERVICE_MAX_BUFFERED`); an over-quota upload is
  shed under an explicit policy (`MASTIC_SERVICE_SHED_POLICY`):
  ``reject-newest`` refuses the incoming upload, ``oldest-epoch-first``
  drops the oldest *pending* (not yet running) epoch to make room.
  Every shed lands in `ServiceCounters.shed_reasons`;

* **epoch scheduler** — `begin_epoch` seals the tenant's buffered
  pages into an epoch; `step()` runs ONE round of one tenant's active
  epoch and round-robins across tenants, so many collection instances
  (Count / Histogram / SumVec at different bit-widths) multiplex
  through the one pipelined executor while admission continues.  The
  scheduler drives every tenant through the `CollectionRun` interface
  (heavy-hitters multi-round, attribute-metrics single-round — the
  DrJAX map/reduce shape: one `step` maps a round over the report
  axis, the aggregate is the reduce);

* **deadlines with graceful degradation** — each epoch gets a
  `Deadline` (`MASTIC_SERVICE_EPOCH_DEADLINE`, defaulting to the r8
  `MASTIC_ROUND_DEADLINE` lever); an epoch that blows it finishes at
  the last completed level and reports the truncated-but-correct
  frontier (`CollectionRun.frontier()`), marked ``truncated`` in its
  result record — degraded output over silent overrun;

* **supervision** — a round that raises is caught, counted, and
  retried a bounded number of times before the epoch is failed; the
  service keeps serving its other tenants either way;

* **crash-resume** — `to_bytes()` extends the r8 snapshot format
  (length-prefixed JSON binding header + npz payload) to cover
  buffered-but-unaggregated pages, queued and active epochs (the
  active run's own checkpoint blob rides inside), and every counter;
  `from_bytes()` restores a service that continues bit-identically
  (pages hold the original upload bytes, and the runs' checkpoint
  machinery is the r5/r8 bit-identity-proven one).  A restored
  epoch's deadline restarts fresh: the budget bounds compute per
  process lifetime, not across crashes.

Fault injection (`MASTIC_FAULTS`, party ``collector``) plugs in at
the ingest seams: checkpoint ``admit`` fires per admission attempt
(kill / hang / delay), checkpoint ``page_flush`` fires per page seal
and its ``corrupt`` / ``truncate`` actions mutate the sealed page's
stored bytes AFTER the digest is taken — modeling storage corruption,
which the digest check must catch — and checkpoints ``epoch_start`` /
``epoch_round`` / ``snapshot`` fire in the scheduler.
"""

import abc
import hashlib
import json
import time
from dataclasses import dataclass
from typing import Optional

import numpy as np

from .. import wire
from ..metrics import ServiceCounters
from ..obs import trace as obs_trace
from ..obs.registry import get_registry
from . import faults as faults_mod
from .session import Deadline, _env_float, _env_int
from .parties import (REASON_MALFORMED, REASON_NAMES, REASON_RANGE,
                      instantiate)
from .attribute_metrics import AttributeMetricsRun
from .heavy_hitters import HeavyHittersRun

# Page-integrity failure: the page's stored bytes no longer match the
# digest taken at seal time (storage corruption; the `page_flush`
# fault models it).  Extends the r8 per-report reason codes.
REASON_PAGE_CORRUPT = 3
SERVICE_REASON_NAMES = dict(REASON_NAMES)
SERVICE_REASON_NAMES[REASON_PAGE_CORRUPT] = "page-corrupt"

SHED_POLICIES = ("reject-newest", "oldest-epoch-first")

# submit() outcomes.
ADMITTED = "admitted"
QUARANTINED = "quarantined"
SHED = "shed"

_SNAPSHOT_VERSION = 1


# -- the scheduler-facing run interface -------------------------------

class CollectionRun(abc.ABC):
    """What the epoch scheduler needs from a collection run — the one
    interface the heavy-hitters multi-round loop, the chunked
    streaming loop (both via `HeavyHittersRun`), and the
    attribute-metrics single round (`AttributeMetricsRun`) all stand
    behind.  `HeavyHittersRun` predates this ABC and is registered as
    a virtual subclass; its checkpoint machinery is the bit-identity
    contract the service snapshot rides on.
    """

    done: bool
    metrics: list

    @abc.abstractmethod
    def step(self) -> bool:
        """Run one round; True while more rounds remain."""

    @abc.abstractmethod
    def result(self):
        """The collection's final output (valid once `done`)."""

    @abc.abstractmethod
    def frontier(self) -> list:
        """The truncated-but-correct output after the last COMPLETED
        round — what a deadline-missed epoch reports.  Every entry
        passed all checks of every completed round; nothing about
        rounds that never ran is claimed."""

    @abc.abstractmethod
    def rounds_completed(self) -> int:
        """Rounds completed over the run's LIFETIME — unlike
        `len(metrics)`, this survives checkpoint-resume (the metrics
        list only covers rounds run in this process)."""

    @abc.abstractmethod
    def to_bytes(self) -> bytes:
        """Checkpoint between rounds (resume must be bit-identical)."""


CollectionRun.register(HeavyHittersRun)
CollectionRun.register(AttributeMetricsRun)

MODES = ("heavy_hitters", "attribute_metrics")


# -- configuration ----------------------------------------------------

def _env_str(name: str, default: str) -> str:
    import os

    raw = os.environ.get(name)
    return default if raw is None or not raw.strip() else raw.strip()


@dataclass
class ServiceConfig:
    """Service-wide levers (env forms in USAGE.md "Collector
    service").  Per-tenant overrides live on `TenantSpec`."""

    page_size: int = 64           # reports per buffer page
    max_buffered: int = 4096      # per-tenant admitted-but-unfinished
    max_pending_epochs: int = 4   # per-tenant queued (not running)
    shed_policy: str = "reject-newest"
    quarantine_limit: int = 64    # per-tenant; past it, suspend
    epoch_deadline: float = 1800.0
    epoch_retries: int = 1        # extra attempts for a failing round

    def __post_init__(self):
        if self.shed_policy not in SHED_POLICIES:
            raise ValueError(
                f"unknown shed policy {self.shed_policy!r} (must be "
                f"one of {', '.join(SHED_POLICIES)})")
        if self.page_size < 1:
            raise ValueError("page_size must be >= 1")

    @classmethod
    def from_env(cls) -> "ServiceConfig":
        return cls(
            page_size=_env_int("MASTIC_SERVICE_PAGE_SIZE", 64),
            max_buffered=_env_int("MASTIC_SERVICE_MAX_BUFFERED", 4096),
            max_pending_epochs=_env_int("MASTIC_SERVICE_MAX_EPOCHS", 4),
            shed_policy=_env_str("MASTIC_SERVICE_SHED_POLICY",
                                 "reject-newest"),
            quarantine_limit=_env_int("MASTIC_SERVICE_QUARANTINE_LIMIT",
                                      64),
            epoch_deadline=_env_float(
                "MASTIC_SERVICE_EPOCH_DEADLINE",
                _env_float("MASTIC_ROUND_DEADLINE", 1800.0)),
            epoch_retries=_env_int("MASTIC_SERVICE_EPOCH_RETRIES", 1),
        )


@dataclass
class TenantSpec:
    """One collection instance (tenant) the service multiplexes.

    `spec` is the r8 party-config instantiation record
    ({"class": "MasticCount", "args": [8]}); `mode` picks the run
    kind; `thresholds` (heavy hitters) / `attributes` (attribute
    metrics) parameterize it.  Optional overrides fall back to the
    service config."""

    name: str
    spec: dict
    ctx: bytes
    verify_key: bytes
    mode: str = "heavy_hitters"
    thresholds: Optional[dict] = None
    attributes: Optional[list] = None
    chunk_size: Optional[int] = None
    page_size: Optional[int] = None
    max_buffered: Optional[int] = None
    epoch_deadline: Optional[float] = None
    quarantine_limit: Optional[int] = None

    def __post_init__(self):
        if self.mode not in MODES:
            raise ValueError(f"unknown tenant mode {self.mode!r} "
                             f"(must be one of {', '.join(MODES)})")
        if self.mode == "heavy_hitters" and not self.thresholds:
            raise ValueError(f"tenant {self.name}: heavy_hitters mode "
                             f"needs thresholds")
        if self.mode == "attribute_metrics" and not self.attributes:
            raise ValueError(f"tenant {self.name}: attribute_metrics "
                             f"mode needs attributes")

    def to_json(self) -> dict:
        return {
            "name": self.name, "spec": self.spec,
            "ctx": self.ctx.hex(), "verify_key": self.verify_key.hex(),
            "mode": self.mode,
            "thresholds": (None if self.thresholds is None
                           else thresholds_to_json(self.thresholds)),
            "attributes": self.attributes,
            "chunk_size": self.chunk_size,
            "page_size": self.page_size,
            "max_buffered": self.max_buffered,
            "epoch_deadline": self.epoch_deadline,
            "quarantine_limit": self.quarantine_limit,
        }

    @classmethod
    def from_json(cls, data: dict) -> "TenantSpec":
        return cls(
            name=data["name"], spec=data["spec"],
            ctx=bytes.fromhex(data["ctx"]),
            verify_key=bytes.fromhex(data["verify_key"]),
            mode=data["mode"],
            thresholds=(None if data["thresholds"] is None
                        else thresholds_from_json(data["thresholds"])),
            attributes=data["attributes"],
            chunk_size=data["chunk_size"],
            page_size=data["page_size"],
            max_buffered=data["max_buffered"],
            epoch_deadline=data["epoch_deadline"],
            quarantine_limit=data["quarantine_limit"],
        )


def thresholds_to_json(thresholds: dict) -> dict:
    """Prefix-tuple keys -> bit strings ("default" passes through)."""
    out = {}
    for (k, v) in thresholds.items():
        if k == "default":
            out[k] = v
        else:
            out["".join("1" if b else "0" for b in k)] = v
    return out


def thresholds_from_json(data: dict) -> dict:
    out = {}
    for (k, v) in data.items():
        if k == "default":
            out[k] = v
        else:
            out[tuple(c == "1" for c in k)] = v
    return out


# -- upload codec (both parties' views in one blob) -------------------

def encode_upload(mastic, report) -> bytes:
    """One client upload as the service ingests it: both aggregators'
    wire-encoded views, framed back to back (clients talk to the
    aggregators directly in a full deployment; the service here is
    the ingest door of the co-located pair)."""
    (nonce, public_share, input_shares) = report
    return (wire.frame(wire.encode_report(mastic, 0, nonce,
                                          public_share,
                                          input_shares[0]))
            + wire.frame(wire.encode_report(mastic, 1, nonce,
                                            public_share,
                                            input_shares[1])))


def decode_upload(mastic, blob: bytes) -> tuple:
    """Validate + decode one upload blob into the drivers' report
    tuple.  Raises ValueError on any malformation — the admission
    path turns that into a reason-coded quarantine."""
    (b0, rest) = wire.unframe(blob)
    (b1, rest) = wire.unframe(rest)
    if rest:
        raise ValueError(f"{len(rest)} trailing bytes after the "
                         f"helper view")
    (nonce0, ps0, share0) = wire.decode_report(mastic, 0, b0)
    (nonce1, _ps1, share1) = wire.decode_report(mastic, 1, b1)
    if nonce0 != nonce1:
        raise ValueError("nonce mismatch between the party views")
    head = mastic.NONCE_SIZE + wire.public_share_size(mastic)
    if b0[:head] != b1[:head]:
        raise ValueError("public share mismatch between the party "
                         "views")
    return (nonce0, ps0, [share0, share1])


def _decode_reason(exc: Exception) -> int:
    """The r8 reason taxonomy (drivers/parties.load_reports)."""
    return (REASON_RANGE if "out of range" in str(exc)
            else REASON_MALFORMED)


# -- paged report buffers ---------------------------------------------

class ReportPage:
    """A fixed-size page of admitted upload blobs.  Open pages accept
    appends; `seal()` freezes the page behind a SHA-256 digest of its
    framed payload, verified every time the page's bytes feed a round
    or cross a snapshot — a corrupted page is detected and dropped,
    never silently aggregated."""

    __slots__ = ("blobs", "count", "payload", "digest")

    def __init__(self):
        self.blobs: list = []
        self.count = 0
        self.payload: Optional[bytes] = None
        self.digest: Optional[bytes] = None

    def append(self, blob: bytes) -> None:
        if self.payload is not None:
            raise ValueError("page is sealed")
        self.blobs.append(blob)
        self.count += 1

    def seal(self) -> None:
        if self.payload is not None:
            return
        self.payload = b"".join(wire.frame(b) for b in self.blobs)
        self.digest = hashlib.sha256(self.payload).digest()
        self.blobs = []

    def verify(self) -> bool:
        if self.payload is None:
            return True   # open page: bytes never left this process
        return hashlib.sha256(self.payload).digest() == self.digest

    def decode_blobs(self) -> list:
        """The page's upload blobs (sealed pages unframe their stored
        payload; digest must be verified by the caller first)."""
        if self.payload is None:
            return list(self.blobs)
        (out, rest) = ([], self.payload)
        while rest:
            (blob, rest) = wire.unframe(rest)
            out.append(blob)
        return out

    @classmethod
    def from_payload(cls, payload: bytes, digest: bytes,
                     count: int) -> "ReportPage":
        page = cls()
        page.payload = payload
        page.digest = digest
        page.count = count
        return page


class _Epoch:
    """One sealed collection epoch: the pages cut from the tenant's
    buffer at begin_epoch, plus (once scheduled) the live run."""

    __slots__ = ("epoch_id", "pages", "run", "reports", "deadline",
                 "failures", "started_at", "reports_lost", "span")

    def __init__(self, epoch_id: int, pages: list):
        self.epoch_id = epoch_id
        self.pages = pages
        self.run = None
        self.reports: Optional[list] = None   # decoded at start
        self.deadline: Optional[Deadline] = None
        self.failures = 0
        self.started_at: Optional[float] = None
        self.reports_lost = 0   # dropped by page-corruption detection
        self.span = None        # open "epoch" trace span while active

    def report_count(self) -> int:
        return sum(p.count for p in self.pages)


class _Tenant:
    __slots__ = ("spec", "mastic", "open_page", "sealed", "pending",
                 "active", "completed", "counters", "epoch_seq",
                 "suspended", "last_timeline")

    def __init__(self, spec: TenantSpec):
        self.spec = spec
        self.mastic = instantiate(spec.spec)
        self.open_page = ReportPage()
        self.sealed: list = []      # sealed pages awaiting an epoch
        self.pending: list = []     # [_Epoch] queued, oldest first
        self.active: Optional[_Epoch] = None
        self.completed: list = []   # epoch result records (dicts)
        self.counters = ServiceCounters(tenant=spec.name)
        # Every tenant's Prometheus series exist from boot (at zero)
        # so a scrape before the first event still sees the family.
        self.counters.export_registry()
        self.epoch_seq = 0
        self.suspended = False
        self.last_timeline: Optional[list] = None  # statusz surface

    def buffered_reports(self) -> int:
        """Reports the tenant holds admitted-but-unfinished — the
        number the admission quota bounds (open + sealed pages,
        queued epochs, and the running epoch)."""
        total = self.open_page.count \
            + sum(p.count for p in self.sealed) \
            + sum(ep.report_count() for ep in self.pending)
        if self.active is not None:
            total += self.active.report_count()
        return total


# -- the service ------------------------------------------------------

class CollectorService:
    """The long-lived, supervised multi-tenant collector (module
    docstring has the full story).  Single-threaded by design: one
    `step()` is one scheduler quantum (one round of one tenant's
    active epoch), and `submit()` may be called between quanta —
    admission lands in the open page, so uploads arriving while
    rounds are in flight join the NEXT epoch."""

    def __init__(self, tenants: list, config: Optional[ServiceConfig]
                 = None, injector=None, mesh=None):
        self.config = config or ServiceConfig.from_env()
        self.mesh = mesh
        self.injector = (injector if injector is not None
                         else faults_mod.injector_from_env("collector"))
        self.tenants: dict = {}
        for spec in tenants:
            if spec.name in self.tenants:
                raise ValueError(f"duplicate tenant {spec.name!r}")
            self.tenants[spec.name] = _Tenant(spec)
        self._rr = 0   # round-robin cursor over tenant order
        self.resumed = False
        # Warm AOT artifact store (drivers/artifacts.py): preload
        # every tenant's program family at boot so the first epoch of
        # each never traces — the ROADMAP item 4 enabler for epoch
        # overlap and containerized serving.
        for t in self.tenants.values():
            self._preload_artifacts(t)

    def add_tenant(self, spec: TenantSpec) -> None:
        """Admit a new collection tenant into the running service
        (fresh buffers/counters; uploads may `submit()` immediately).
        Its artifact family preloads right here, so with a baked
        store the new tenant's first round pays disk loads at
        admission time, not a trace at epoch time."""
        if spec.name in self.tenants:
            raise ValueError(f"duplicate tenant {spec.name!r}")
        t = _Tenant(spec)
        self.tenants[spec.name] = t
        self._preload_artifacts(t)

    def _preload_artifacts(self, t: _Tenant) -> None:
        """Pull the tenant's program family (instantiation + ctx)
        from the AOT store into memory — digest-gated and probe-
        verified per artifact (artifacts.ArtifactStore.load); every
        outcome lands in mastic_artifact_loads_total."""
        from ..backend.mastic_jax import BatchedMastic
        from . import artifacts

        store = artifacts.store_from_env()
        if store is None:
            return
        fam = artifacts.family_id(BatchedMastic(t.mastic), t.spec.ctx)
        counts = store.preload(lambda key: key[-1] == fam)
        if counts:
            obs_trace.event("artifact_preload", tenant=t.spec.name,
                            store=store.path, **counts)

    # -- small config helpers --------------------------------------

    def _page_size(self, t: _Tenant) -> int:
        return t.spec.page_size or self.config.page_size

    def _max_buffered(self, t: _Tenant) -> int:
        return t.spec.max_buffered or self.config.max_buffered

    def _quarantine_limit(self, t: _Tenant) -> int:
        return (t.spec.quarantine_limit
                if t.spec.quarantine_limit is not None
                else self.config.quarantine_limit)

    def _epoch_deadline(self, t: _Tenant) -> float:
        return (t.spec.epoch_deadline
                if t.spec.epoch_deadline is not None
                else self.config.epoch_deadline)

    def _checkpoint(self, step: str) -> None:
        if self.injector is not None:
            self.injector.checkpoint(step)

    # -- admission -------------------------------------------------

    def submit(self, tenant: str, blob: bytes) -> tuple:
        """Admit one upload blob for `tenant`.  Returns (status,
        detail): ADMITTED, QUARANTINED (detail = reason name), or
        SHED (detail = policy / reason).  Never raises for bad input
        — a hostile upload must cost the service one decode, not an
        exception path."""
        t = self.tenants[tenant]
        self._checkpoint("admit")
        if t.suspended:
            t.counters.inc("shed")
            t.counters.bump_shed("tenant-quarantined")
            obs_trace.event("shed", tenant=tenant,
                            reason="tenant-quarantined")
            return (SHED, "tenant-quarantined")
        try:
            decode_upload(t.mastic, blob)
        except (ValueError, EOFError) as exc:
            reason = _decode_reason(exc)
            t.counters.inc("quarantined")
            t.counters.bump_quarantine(SERVICE_REASON_NAMES[reason])
            obs_trace.event("quarantine", tenant=tenant,
                            reason=SERVICE_REASON_NAMES[reason])
            if t.counters.quarantined >= self._quarantine_limit(t):
                t.suspended = True
                obs_trace.event("tenant_suspended", tenant=tenant,
                                quarantined=t.counters.quarantined)
            return (QUARANTINED, SERVICE_REASON_NAMES[reason])
        if t.buffered_reports() >= self._max_buffered(t):
            # oldest-epoch-first may make room by dropping a queued
            # epoch; if the buffer is still over quota after that (or
            # the policy is reject-newest), the incoming upload sheds.
            self._shed(t)
            if t.buffered_reports() >= self._max_buffered(t):
                t.counters.inc("shed")
                t.counters.bump_shed("reject-newest")
                obs_trace.event("shed", tenant=tenant,
                                reason="reject-newest")
                return (SHED, "reject-newest")
        t.open_page.append(blob)
        t.counters.inc("admitted")
        if t.open_page.count >= self._page_size(t):
            self._seal_open_page(t)
        return (ADMITTED, "")

    def _shed(self, t: _Tenant) -> Optional[str]:
        """Over-quota relief under the configured policy.  Returns the
        shed detail when room was made (oldest-epoch-first), None when
        the incoming upload itself must be rejected."""
        if self.config.shed_policy != "oldest-epoch-first" \
                or not t.pending:
            return None
        victim = t.pending.pop(0)
        lost = victim.report_count()
        t.counters.inc("shed", lost)
        t.counters.bump_shed("oldest-epoch-first", lost)
        obs_trace.event("shed", tenant=t.spec.name,
                        reason="oldest-epoch-first", reports=lost,
                        epoch=victim.epoch_id)
        return f"oldest-epoch-first dropped epoch {victim.epoch_id} " \
               f"({lost} reports)"

    def _seal_open_page(self, t: _Tenant) -> None:
        page = t.open_page
        t.open_page = ReportPage()
        page.seal()
        if self.injector is not None:
            # One fault event per seal: kill/hang/delay fire as
            # process faults, truncate/corrupt mutate the stored
            # bytes AFTER the digest (storage-corruption model — the
            # verify() gate must catch it downstream).
            page.payload = self.injector.on_blob("page_flush",
                                                 page.payload)
        t.sealed.append(page)
        t.counters.inc("pages_sealed")

    # -- epochs ----------------------------------------------------

    def begin_epoch(self, tenant: str) -> Optional[int]:
        """Cut the tenant's buffered pages into a new pending epoch.
        Returns the epoch id, or None when there is nothing buffered
        or the pending queue is full under reject-newest (the pages
        stay buffered for a later cut)."""
        t = self.tenants[tenant]
        if t.open_page.count:
            self._seal_open_page(t)
        if not t.sealed:
            return None
        if len(t.pending) >= self.config.max_pending_epochs:
            if self._shed(t) is None:
                # reject-newest: the cut is refused (pages stay
                # buffered for a later attempt), counted, not silent.
                t.counters.inc("epochs_refused")
                return None
        epoch = _Epoch(t.epoch_seq, t.sealed)
        t.epoch_seq += 1
        t.sealed = []
        t.pending.append(epoch)
        return epoch.epoch_id

    def _build_run(self, t: _Tenant, reports: list) -> CollectionRun:
        spec = t.spec
        if spec.mode == "heavy_hitters":
            run = HeavyHittersRun(
                t.mastic, spec.ctx, spec.thresholds, reports,
                verify_key=spec.verify_key,
                chunk_size=spec.chunk_size, mesh=self.mesh)
        else:
            run = AttributeMetricsRun(
                t.mastic, spec.ctx, spec.attributes, reports,
                verify_key=spec.verify_key,
                chunk_size=spec.chunk_size, mesh=self.mesh)
        # The run's round spans / registry series carry this tenant.
        run.obs_tenant = spec.name
        return run

    def _restore_run(self, t: _Tenant, reports: list,
                     blob: bytes) -> CollectionRun:
        spec = t.spec
        if spec.mode == "heavy_hitters":
            run = HeavyHittersRun.from_bytes(
                t.mastic, spec.ctx, spec.thresholds, reports,
                spec.verify_key, blob, mesh=self.mesh)
        else:
            run = AttributeMetricsRun.from_bytes(
                t.mastic, spec.ctx, spec.attributes, reports,
                spec.verify_key, blob, chunk_size=spec.chunk_size,
                mesh=self.mesh)
        run.obs_tenant = spec.name
        return run

    def _epoch_reports(self, t: _Tenant, epoch: _Epoch) -> list:
        """Decode the epoch's pages into the drivers' report tuples,
        dropping (and counting) any page whose digest check fails —
        a corrupted page degrades the epoch, never poisons it."""
        reports = []
        surviving = []
        for page in epoch.pages:
            if not page.verify():
                epoch.reports_lost += page.count
                t.counters.inc("pages_corrupt")
                t.counters.inc("quarantined", page.count)
                t.counters.bump_quarantine(
                    SERVICE_REASON_NAMES[REASON_PAGE_CORRUPT],
                    page.count)
                obs_trace.event(
                    "page_corrupt", tenant=t.spec.name,
                    epoch=epoch.epoch_id, reports=page.count)
                continue
            surviving.append(page)
            for blob in page.decode_blobs():
                # Admission already validated the blob; decode again
                # so the run consumes exactly the persisted bytes.
                reports.append(decode_upload(t.mastic, blob))
        epoch.pages = surviving
        return reports

    def _start_epoch(self, t: _Tenant) -> None:
        epoch = t.pending.pop(0)
        self._checkpoint("epoch_start")
        epoch.span = obs_trace.get_tracer().start_detached_span(
            "epoch", tenant=t.spec.name, epoch=epoch.epoch_id,
            reports=epoch.report_count())
        reports = self._epoch_reports(t, epoch)
        if not reports:
            # Every page was corrupt (or the epoch was empty): an
            # immediately-final degraded epoch, counted, not raised.
            t.counters.inc("epochs_started")
            t.counters.inc("epochs_failed")
            t.completed.append(self._record(t, epoch, result=[],
                                            truncated=True,
                                            levels=0, error="no "
                                            "surviving reports"))
            return
        epoch.reports = reports
        t.counters.inc("epochs_started")
        try:
            epoch.run = self._build_run(t, reports)
        except Exception as exc:
            # Run construction can refuse (e.g. a memory-envelope
            # gate for the tenant's chunk config): a config-sick
            # tenant fails ITS epoch, attributably — not the service.
            t.counters.inc("epochs_failed")
            t.completed.append(self._record(
                t, epoch, result=[], truncated=True, levels=0,
                error=f"{type(exc).__name__}: {exc}"))
            return
        epoch.deadline = Deadline(self._epoch_deadline(t))
        epoch.started_at = time.monotonic()
        t.active = epoch

    def _record(self, t: _Tenant, epoch: _Epoch, result,
                truncated: bool, levels: int,
                error: Optional[str] = None) -> dict:
        rec = {
            "tenant": t.spec.name,
            "epoch": epoch.epoch_id,
            "reports": epoch.report_count(),
            "reports_lost": epoch.reports_lost,
            "result": _jsonable(result),
            "truncated": truncated,
            "levels_completed": levels,
        }
        if epoch.started_at is not None:
            rec["wall_s"] = round(time.monotonic() - epoch.started_at,
                                  3)
        if error is not None:
            rec["error"] = error
        if epoch.span is not None:
            # The epoch's trace span closes with its outcome; every
            # round span of the epoch parented to it.
            epoch.span.set(truncated=truncated, levels=levels,
                           **({"error": error} if error else {}))
            obs_trace.get_tracer().end_span(epoch.span)
            epoch.span = None
        return rec

    # -- the scheduler ---------------------------------------------

    def step(self) -> bool:
        """One scheduler quantum: pick the next tenant (round-robin)
        with work, run one round of its active epoch (starting the
        oldest pending epoch if none is active), and return whether
        any tenant still has epoch work queued or running."""
        names = list(self.tenants)
        for off in range(len(names)):
            t = self.tenants[names[(self._rr + off) % len(names)]]
            if t.active is None and t.pending:
                self._start_epoch(t)
            if t.active is None:
                continue
            self._rr = (self._rr + off + 1) % len(names)
            self._run_one_round(t)
            break
        return any(t.active is not None or t.pending
                   for t in self.tenants.values())

    def _run_one_round(self, t: _Tenant) -> None:
        epoch = t.active
        self._checkpoint("epoch_round")
        tracer = obs_trace.get_tracer()
        if epoch.deadline.expired():
            # Graceful degradation: finish at the last completed
            # level; the frontier is correct for every round that ran.
            t.counters.inc("deadline_misses")
            t.counters.inc("epochs_truncated")
            if epoch.span is not None:
                epoch.span.event("deadline_miss",
                                 levels=epoch.run.rounds_completed())
            t.completed.append(self._record(
                t, epoch, result=epoch.run.frontier(),
                truncated=True,
                levels=epoch.run.rounds_completed()))
            t.active = None
            return
        t0 = time.perf_counter()
        before = len(epoch.run.metrics)
        try:
            # The run's own round span (HeavyHittersRun.step /
            # AttributeMetricsRun.step) parents to this tenant's open
            # epoch span — NOT to whatever epoch started last.
            with tracer.use_parent(epoch.span):
                more = epoch.run.step()
        except Exception as exc:   # supervised: fail the epoch, not
            # the service — other tenants keep their schedule
            epoch.failures += 1
            if epoch.failures > self.config.epoch_retries:
                t.counters.inc("epochs_failed")
                t.completed.append(self._record(
                    t, epoch, result=epoch.run.frontier(),
                    truncated=True,
                    levels=epoch.run.rounds_completed(),
                    error=f"{type(exc).__name__}: {exc}"))
                t.active = None
            else:
                # A round that raises mid-execution can leave the
                # runner's device carries inconsistent, so the retry
                # REBUILDS the run from the epoch's pages — prep is a
                # pure function of the reports, so the restart is
                # bit-identical (completed levels recompute; the r8
                # respawn-and-replay model applied in-process).
                if epoch.span is not None:
                    epoch.span.event(
                        "epoch_retry", attempt=epoch.failures,
                        cause=f"{type(exc).__name__}: {exc}"[:200])
                get_registry().counter(
                    "mastic_session_retries_total",
                    tenant=t.spec.name).inc()
                epoch.run = self._build_run(t, epoch.reports)
            return
        t.counters.inc("rounds")
        quantum_ms = (time.perf_counter() - t0) * 1e3
        reg = get_registry()
        for mx in epoch.run.metrics[before:]:
            round_ms = mx.extra.get("round_wall_ms", 0.0)
            sched_ms = round(max(0.0, quantum_ms - round_ms), 3)
            mx.extra["service"] = {
                "tenant": t.spec.name,
                "epoch": epoch.epoch_id,
                "sched_overhead_ms": sched_ms,
                "buffered_reports": t.buffered_reports(),
                "pending_epochs": len(t.pending),
            }
            # The service block joins the unified extra schema
            # (re-stamp: the driver already validated its own blocks).
            mx.validate_extra()
            reg.counter("mastic_sched_overhead_ms_total",
                        tenant=t.spec.name).inc(sched_ms)
            if mx.extra.get("chunks"):
                t.last_timeline = mx.extra["chunks"]
        reg.gauge("mastic_buffered_reports",
                  tenant=t.spec.name).set(t.buffered_reports())
        reg.gauge("mastic_pending_epochs",
                  tenant=t.spec.name).set(len(t.pending))
        if not more:
            t.counters.inc("epochs_completed")
            t.completed.append(self._record(
                t, epoch, result=epoch.run.result(), truncated=False,
                levels=epoch.run.rounds_completed()))
            t.active = None

    def run_until_drained(self,
                          deadline: Optional[Deadline] = None) -> bool:
        """Drive the scheduler until no epoch work remains.  Returns
        False when `deadline` expired first (remaining work stays
        queued — snapshot and resume, or keep stepping)."""
        while self.step():
            if deadline is not None and deadline.expired():
                return False
        return True

    def drained(self) -> bool:
        return not any(t.active is not None or t.pending
                       for t in self.tenants.values())

    # -- observability ---------------------------------------------

    def metrics(self) -> dict:
        """The service metrics JSON: per-tenant counters, buffer
        occupancy, quarantine/shed reason tables, epoch records."""
        out = {"policy": self.config.shed_policy,
               "resumed": self.resumed, "tenants": {}}
        for (name, t) in self.tenants.items():
            out["tenants"][name] = {
                "buffered_reports": t.buffered_reports(),
                "open_page": t.open_page.count,
                "sealed_pages": len(t.sealed),
                "pending_epochs": len(t.pending),
                "active_epoch": (t.active.epoch_id
                                 if t.active is not None else None),
                "suspended": t.suspended,
                "counters": t.counters.as_dict(),
                "epochs": list(t.completed),
                # The statusz last-round timeline (per-chunk phases
                # of the tenant's most recent chunked round).
                "last_round_timeline": t.last_timeline,
            }
        return out

    # -- snapshot / resume -----------------------------------------

    def to_bytes(self) -> bytes:
        """Snapshot everything a crash must not lose: buffered pages
        (open + sealed), queued epochs, the active epoch's pages and
        its run checkpoint, completed results, and counters — the r8
        snapshot format (length-prefixed JSON binding header + npz
        payload), extended to the ingest layer."""
        import io

        self._checkpoint("snapshot")
        header = json.dumps({
            "version": _SNAPSHOT_VERSION,
            "policy": self.config.shed_policy,
            "tenants": [t.spec.to_json()
                        for t in self.tenants.values()],
        }, sort_keys=True).encode()
        data: dict = {"meta": np.array(
            [_SNAPSHOT_VERSION, len(self.tenants)], np.int64)}

        def put_page(prefix: str, page: ReportPage) -> None:
            sealed = page.payload is not None
            payload = (page.payload if sealed
                       else b"".join(wire.frame(b)
                                     for b in page.blobs))
            data[prefix] = np.frombuffer(payload, np.uint8)
            data[prefix + "_meta"] = np.array(
                [page.count, int(sealed)], np.int64)
            data[prefix + "_digest"] = np.frombuffer(
                page.digest if sealed else b"\x00" * 32, np.uint8)

        def put_epoch(prefix: str, epoch: _Epoch) -> None:
            data[prefix + "_meta"] = np.array(
                [epoch.epoch_id, len(epoch.pages),
                 epoch.reports_lost], np.int64)
            for (j, page) in enumerate(epoch.pages):
                put_page(f"{prefix}_pg{j}", page)

        for (i, t) in enumerate(self.tenants.values()):
            data[f"t{i}_state"] = np.array(
                [t.epoch_seq, int(t.suspended), len(t.sealed),
                 len(t.pending), int(t.active is not None)], np.int64)
            data[f"t{i}_counters"] = np.frombuffer(
                json.dumps(t.counters.as_dict()).encode(), np.uint8)
            data[f"t{i}_completed"] = np.frombuffer(
                json.dumps(t.completed).encode(), np.uint8)
            put_page(f"t{i}_open", t.open_page)
            for (j, page) in enumerate(t.sealed):
                put_page(f"t{i}_s{j}", page)
            for (k, epoch) in enumerate(t.pending):
                put_epoch(f"t{i}_p{k}", epoch)
            if t.active is not None:
                put_epoch(f"t{i}_active", t.active)
                data[f"t{i}_active_run"] = np.frombuffer(
                    t.active.run.to_bytes(), np.uint8)
        buf = io.BytesIO()
        np.savez(buf, **data)
        return (len(header).to_bytes(4, "little") + header
                + buf.getvalue())

    @classmethod
    def from_bytes(cls, data: bytes,
                   config: Optional[ServiceConfig] = None,
                   injector=None, mesh=None) -> "CollectorService":
        """Restore a snapshotted service.  Page digests are verified
        as epochs start (a snapshot corrupted in storage degrades the
        affected epoch, detected, instead of aggregating garbage);
        the active epoch's run resumes bit-identically from its own
        checkpoint blob.  Its deadline restarts fresh — the budget
        bounds compute per process lifetime."""
        import io

        hlen = int.from_bytes(data[:4], "little")
        try:
            header = json.loads(data[4:4 + hlen])
        except ValueError:
            raise ValueError(
                "service snapshot has no JSON binding header — not a "
                "snapshot written by CollectorService.to_bytes")
        if header.get("version") != _SNAPSHOT_VERSION:
            raise ValueError(f"unknown service snapshot version "
                             f"{header.get('version')}")
        arrays = np.load(io.BytesIO(data[4 + hlen:]),
                         allow_pickle=False)
        specs = [TenantSpec.from_json(d) for d in header["tenants"]]
        if config is None:
            config = ServiceConfig.from_env()
        config.shed_policy = header["policy"]
        svc = cls(specs, config=config, injector=injector, mesh=mesh)
        svc.resumed = True

        def get_page(prefix: str) -> ReportPage:
            payload = arrays[prefix].tobytes()
            (count, sealed) = [int(x)
                               for x in arrays[prefix + "_meta"]]
            digest = arrays[prefix + "_digest"].tobytes()
            if sealed:
                return ReportPage.from_payload(payload, digest, count)
            page = ReportPage()
            rest = payload
            while rest:   # mastic-allow: RB005 — bounded by the
                # stored open-page payload length
                (blob, rest) = wire.unframe(rest)
                page.append(blob)
            return page

        def get_epoch(prefix: str) -> _Epoch:
            (epoch_id, npages, lost) = [
                int(x) for x in arrays[prefix + "_meta"]]
            epoch = _Epoch(epoch_id, [get_page(f"{prefix}_pg{j}")
                                      for j in range(npages)])
            epoch.reports_lost = lost
            return epoch

        for (i, t) in enumerate(svc.tenants.values()):
            (seq, susp, nsealed, npending, has_active) = [
                int(x) for x in arrays[f"t{i}_state"]]
            t.epoch_seq = seq
            t.suspended = bool(susp)
            restored = json.loads(arrays[f"t{i}_counters"].tobytes())
            # Pre-ISSUE-7 snapshots carry no tenant label.
            restored.setdefault("tenant", t.spec.name)
            t.counters = ServiceCounters.from_dict(restored)
            t.counters.resumes += 1
            # Republish the persisted totals so the Prometheus series
            # continue where the crashed process left them.
            t.counters.export_registry()
            t.completed = json.loads(
                arrays[f"t{i}_completed"].tobytes())
            t.open_page = get_page(f"t{i}_open")
            t.sealed = [get_page(f"t{i}_s{j}")
                        for j in range(nsealed)]
            t.pending = [get_epoch(f"t{i}_p{k}")
                         for k in range(npending)]
            if has_active:
                epoch = get_epoch(f"t{i}_active")
                reports = svc._epoch_reports(t, epoch)
                if not reports:
                    t.counters.epochs_failed += 1
                    t.completed.append(svc._record(
                        t, epoch, result=[], truncated=True,
                        levels=0, error="no surviving reports after "
                        "resume"))
                else:
                    epoch.reports = reports
                    epoch.run = svc._restore_run(
                        t, reports, arrays[f"t{i}_active_run"]
                        .tobytes())
                    epoch.deadline = Deadline(svc._epoch_deadline(t))
                    epoch.started_at = time.monotonic()
                    epoch.span = obs_trace.get_tracer() \
                        .start_detached_span(
                            "epoch", tenant=t.spec.name,
                            epoch=epoch.epoch_id,
                            reports=epoch.report_count(),
                            resumed=True)
                    t.active = epoch
        return svc


def _jsonable(result):
    """Epoch results as JSON-safe values (heavy-hitter prefixes are
    bool tuples; attribute aggregates are (name, value) pairs)."""
    if isinstance(result, (list, tuple)):
        return [_jsonable(x) for x in result]
    if isinstance(result, (bool, np.bool_)):
        return bool(result)
    if isinstance(result, (int, np.integer)):
        return int(result)
    return result
