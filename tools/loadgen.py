"""Million-client load driver for the network front (ISSUE 11,
`mastic_tpu/net/loadgen.py`): drive the DAP-shaped upload endpoint
with a zipf/Poisson/burst client mix and stamp the first end-to-end
SLO numbers this repo has — the `serve-load` bench cell.

Modes:

* default (``--self``) — boot a collector service + upload front
  in-process, run one load phase from the CLI profile (``--clients``,
  ``--rate``, ``--duration`` …), and print one JSON line with
  admission-latency quantiles (p50/p95/p99), achieved reports/s, the
  HTTP code mix, and the service's shed/quarantine ledger.  The run
  FAILS (exit 1) when the stated SLO (``--slo-p99-ms``) is missed or
  any request goes unaccounted;

* ``--target http://host:port`` — drive an already-running endpoint
  (`tools/serve.py --upload-port`) instead of self-hosting (no
  service introspection — the endpoint's own /metrics has the server
  side);

* ``--smoke`` — the `make net-smoke` gate, four phases:

  1. **slo** — 10^5 simulated clients (zipf popularity, distinct
     X-Forwarded-For addresses), Poisson arrivals with bursts, a
     malformed fraction: every request answered 201/400, response
     counts equal to the service's counter deltas EXACTLY (zero
     lost, zero duplicated, zero silent), p99 admission latency
     within the SLO;
  2. **knee** — offered load far past the admission quota: the
     service degrades BY POLICY — the first `max_buffered` uploads
     admit, everything after sheds 429 + Retry-After with the drop
     reason-coded in `shed_reasons`, zero 5xx, the whole mix summing
     exactly;
  3. **ratelimit** — one hot client against the per-IP token bucket
     (`MASTIC_NET_RATE` semantics): burst admits, sustained excess
     429s with ``rate-limited`` in the tenant's shed ledger;
  4. **kill9** — the mid-upload crash drill over `tools/serve.py
     --upload-port --snapshot`: a clean child, a child killed -9 by
     the injector mid-upload (after 3 of 6 acked), and a ``--resume``
     child the client retries its un-acked uploads against; the
     resumed collection's results must equal the clean run's bit for
     bit and the admitted total must be exactly 6 (at-least-once
     client retry + snapshot-before-ack = exactly-once admission).

Recipes in USAGE.md "Network front"; measured numbers in PERF.md §13.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def fail(msg: str) -> None:
    print(f"loadgen: FAIL: {msg}", file=sys.stderr, flush=True)
    sys.exit(1)


def build_service(bits: int, max_buffered: int, ingest_threads: int,
                  ingest_queue: int, quarantine_limit: int = 10 ** 9):
    """A two-tenant collector for self-hosted load phases.  The
    quarantine limit defaults to effectively-unbounded: load phases
    deliberately stream malformed uploads, and the per-tenant
    suspension defense would otherwise (correctly) shut the tenant —
    that defense has its own serve-smoke coverage."""
    import numpy as np

    from mastic_tpu.drivers.service import (CollectorService,
                                            ServiceConfig, TenantSpec)
    from mastic_tpu.mastic import MasticCount

    rng = np.random.default_rng(7)
    m_count = MasticCount(bits)
    m_attrs = MasticCount(8)
    vk = bytes(rng.integers(0, 256, m_count.VERIFY_KEY_SIZE,
                            dtype="uint8"))
    vk2 = bytes(rng.integers(0, 256, m_attrs.VERIFY_KEY_SIZE,
                             dtype="uint8"))
    specs = [
        TenantSpec(name="count",
                   spec={"class": "MasticCount", "args": [bits]},
                   ctx=b"loadgen count", verify_key=vk,
                   thresholds={"default": 2}),
        TenantSpec(name="attrs",
                   spec={"class": "MasticCount", "args": [8]},
                   ctx=b"loadgen attrs", verify_key=vk2,
                   thresholds={"default": 2}),
    ]
    cfg = ServiceConfig(page_size=64, max_buffered=max_buffered,
                        max_pending_epochs=64,
                        quarantine_limit=quarantine_limit,
                        epoch_deadline=3600.0,
                        ingest_threads=ingest_threads,
                        ingest_queue=ingest_queue)
    svc = CollectorService(specs, config=cfg)
    return (svc, {"count": (m_count, b"loadgen count"),
                  "attrs": (m_attrs, b"loadgen attrs")})


def build_pools(tenants: dict, bits: int, pool: int,
                replay: int) -> dict:
    import numpy as np

    from mastic_tpu.net import loadgen

    rng = np.random.default_rng(replay + 1)
    pools = {}
    for (i, (name, (m, ctx))) in enumerate(sorted(tenants.items())):
        t_bits = m.vidpf.BITS
        valid = loadgen.build_blob_pool(m, ctx, pool, t_bits,
                                        replay=replay + i)
        pools[name] = {
            "valid": valid,
            "malformed": [loadgen.malform(b, rng)
                          for b in valid[:max(1, pool // 4)]],
        }
    return pools


def counter_totals(svc) -> dict:
    totals = {"admitted": 0, "quarantined": 0, "shed": 0,
              "shed_reasons": {}, "quarantine_reasons": {}}
    for t in svc.metrics()["tenants"].values():
        c = t["counters"]
        totals["admitted"] += c["admitted"]
        totals["quarantined"] += c["quarantined"]
        totals["shed"] += c["shed"]
        for (k, v) in c["shed_reasons"].items():
            totals["shed_reasons"][k] = \
                totals["shed_reasons"].get(k, 0) + v
        for (k, v) in c["quarantine_reasons"].items():
            totals["quarantine_reasons"][k] = \
                totals["quarantine_reasons"].get(k, 0) + v
    return totals


def run_phase(svc, front, profile, pools) -> dict:
    """One load phase against a live front, with the before/after
    counter deltas folded in."""
    from mastic_tpu.net.loadgen import LoadGenerator

    before = counter_totals(svc)
    gen = LoadGenerator("127.0.0.1", front.port, profile, pools)
    rec = gen.run()
    svc.flush_ingest()
    after = counter_totals(svc)
    rec["service"] = {
        "admitted": after["admitted"] - before["admitted"],
        "quarantined": after["quarantined"] - before["quarantined"],
        "shed": after["shed"] - before["shed"],
        "shed_reasons": {
            k: v - before["shed_reasons"].get(k, 0)
            for (k, v) in after["shed_reasons"].items()
            if v - before["shed_reasons"].get(k, 0)},
        "quarantine_reasons": {
            k: v - before["quarantine_reasons"].get(k, 0)
            for (k, v) in after["quarantine_reasons"].items()
            if v - before["quarantine_reasons"].get(k, 0)},
    }
    return rec


def check_accounting(rec: dict, phase: str) -> None:
    """The no-silent-drops ledger: every answered request is counted
    in exactly one service ledger (shed at the door included), so
    responses and counters must sum to the same total."""
    svc = rec["service"]
    answered = rec["answered"]
    landed = svc["admitted"] + svc["quarantined"] + svc["shed"]
    if rec["transport_errors"]:
        fail(f"{phase}: {rec['transport_errors']} transport errors "
             f"(client-visible drops)")
    if landed != answered:
        fail(f"{phase}: {answered} answered requests vs "
             f"{landed} ledger entries — a drop went uncounted: "
             f"{svc}")
    for code in rec["codes"]:
        if code.startswith("5"):
            fail(f"{phase}: {rec['codes'][code]} x HTTP {code} — "
                 f"degradation must be by policy, never an error")


def phase_slo(args) -> dict:
    """Phase 1: the stated SLO at the stated client scale."""
    from mastic_tpu.net.ingest import UploadFront
    from mastic_tpu.net.admission import NetConfig
    from mastic_tpu.net.loadgen import LoadProfile, buffered_blobs

    (svc, tenants) = build_service(bits=2, max_buffered=10 ** 6,
                                   ingest_threads=0, ingest_queue=256)
    pools = build_pools(tenants, 2, pool=64, replay=args.replay)
    front = UploadFront(
        svc, config=NetConfig(max_connections=256,
                              trust_forwarded=True)).start()
    profile = LoadProfile(
        clients=args.clients, duration_s=args.duration,
        rate=args.rate, burst_factor=3.0, malformed_frac=0.03,
        zipf_s=1.2, workers=args.workers, replay=args.replay,
        tenant_weights={"count": 0.8, "attrs": 0.2})
    rec = run_phase(svc, front, profile, pools)
    front.stop()
    check_accounting(rec, "slo")
    unexpected = set(rec["codes"]) - {"201", "400"}
    if unexpected:
        fail(f"slo: unexpected response codes {sorted(unexpected)} "
             f"(mix: {rec['codes']})")
    if rec["codes"].get("400", 0) != rec["service"]["quarantined"]:
        fail(f"slo: 400s {rec['codes'].get('400', 0)} != quarantined "
             f"{rec['service']['quarantined']}")
    buffered = sum(len(buffered_blobs(svc, t)) for t in tenants)
    if buffered != rec["service"]["admitted"]:
        fail(f"slo: {rec['service']['admitted']} admitted but "
             f"{buffered} buffered — lost or duplicated reports")
    p99 = rec["latency_ms"]["p99"]
    if p99 is None or p99 > args.slo_p99_ms:
        fail(f"slo: p99 admission latency {p99} ms over the "
             f"{args.slo_p99_ms} ms SLO")
    if rec["distinct_clients_seen"] < 100:
        fail(f"slo: only {rec['distinct_clients_seen']} distinct "
             f"clients seen")
    rec["slo_p99_ms"] = args.slo_p99_ms
    rec["slo_held"] = True
    return rec


def phase_knee(args) -> dict:
    """Phase 2: past the knee, degradation is by policy."""
    from mastic_tpu.net.ingest import UploadFront
    from mastic_tpu.net.admission import NetConfig
    from mastic_tpu.net.loadgen import LoadProfile

    quota = 250
    (svc, tenants) = build_service(bits=2, max_buffered=quota,
                                   ingest_threads=0, ingest_queue=64)
    pools = build_pools(tenants, 2, pool=64, replay=args.replay + 10)
    front = UploadFront(
        svc, config=NetConfig(max_connections=256,
                              trust_forwarded=True)).start()
    profile = LoadProfile(
        clients=args.clients, duration_s=max(2.0, args.duration / 2),
        rate=args.rate * 6, burst_factor=2.0, malformed_frac=0.0,
        zipf_s=1.2, workers=args.workers * 2, replay=args.replay + 10,
        tenant_weights={"count": 0.8, "attrs": 0.2})
    rec = run_phase(svc, front, profile, pools)
    front.stop()
    check_accounting(rec, "knee")
    shed = rec["service"]["shed"]
    if rec["codes"].get("429", 0) != shed or shed == 0:
        fail(f"knee: 429s {rec['codes'].get('429', 0)} != shed "
             f"{shed} (mix {rec['codes']})")
    if rec["retry_after_seen"] < rec["codes"].get("429", 0):
        fail(f"knee: {rec['codes'].get('429', 0)} 429s but only "
             f"{rec['retry_after_seen']} Retry-After headers")
    known = {"reject-newest", "oldest-epoch-first",
             "ingest-queue-full", "rate-limited",
             "connections-exhausted", "body-too-large",
             "incomplete-body", "tenant-quarantined"}
    bad = set(rec["service"]["shed_reasons"]) - known
    if bad:
        fail(f"knee: unknown shed reasons {sorted(bad)}")
    # Both tenants hold exactly their quota: the knee is per-tenant
    # admission policy, not first-come starvation across tenants.
    per_tenant = {name: t["counters"]["admitted"]
                  for (name, t) in svc.metrics()["tenants"].items()}
    for (name, admitted) in per_tenant.items():
        if admitted > quota:
            fail(f"knee: tenant {name} admitted {admitted} past its "
                 f"{quota} quota")
    rec["per_tenant_admitted"] = per_tenant
    rec["quota"] = quota
    return rec


def phase_ratelimit(args) -> dict:
    """Phase 3: the per-IP token bucket, one hot client."""
    from http.client import HTTPConnection

    from mastic_tpu.net.admission import NetConfig
    from mastic_tpu.net.ingest import MEDIA_TYPE, UploadFront

    (svc, tenants) = build_service(bits=2, max_buffered=10 ** 6,
                                   ingest_threads=0, ingest_queue=64)
    pools = build_pools(tenants, 2, pool=8, replay=args.replay + 20)
    # rate=5/s: one token per 200 ms, far slower than a loopback
    # HTTP roundtrip, so the 20-request hammer MUST exhaust the
    # 5-token burst regardless of fabric speed.
    front = UploadFront(
        svc, config=NetConfig(rate=5.0, burst=5.0,
                              trust_forwarded=True)).start()
    blob = pools["count"]["valid"][0]
    conn = HTTPConnection("127.0.0.1", front.port, timeout=10)
    codes = {}
    retry_after = 0
    for _ in range(20):
        conn.request("PUT", "/v1/tenants/count/reports", body=blob,
                     headers={"Content-Type": MEDIA_TYPE,
                              "X-Forwarded-For": "10.9.9.9"})
        resp = conn.getresponse()
        resp.read()
        codes[resp.status] = codes.get(resp.status, 0) + 1
        if resp.getheader("Retry-After"):
            retry_after += 1
    conn.close()
    front.stop()
    sheds = counter_totals(svc)["shed_reasons"]
    if codes.get(429, 0) == 0 or sheds.get("rate-limited", 0) == 0:
        fail(f"ratelimit: bucket never fired (codes {codes}, "
             f"sheds {sheds})")
    if codes.get(429, 0) != sheds.get("rate-limited", 0):
        fail(f"ratelimit: 429s {codes.get(429, 0)} != rate-limited "
             f"sheds {sheds.get('rate-limited', 0)}")
    if retry_after < codes.get(429, 0):
        fail(f"ratelimit: Retry-After missing on some 429s")
    return {"codes": {str(k): v for (k, v) in sorted(codes.items())},
            "rate_limited_sheds": sheds.get("rate-limited", 0),
            "bucket": {"rate": 5.0, "burst": 5.0}}


def _wait_port(path: str, deadline_s: float = 120.0) -> int:
    t0 = time.monotonic()
    last_error = "file never appeared"
    while time.monotonic() - t0 < deadline_s:
        if os.path.exists(path):
            try:
                with open(path) as f:
                    return json.load(f)["upload_port"]
            except (ValueError, KeyError) as exc:
                # Mid-rename torn read; retried until the deadline
                # names the last failure.
                last_error = f"{type(exc).__name__}: {exc}"
        time.sleep(0.1)
    fail(f"kill9: no upload port from {path} ({last_error})")


def run_upload_drill(args, tmp: str) -> dict:
    """Phase 4: kill -9 mid-upload, resume via serve.py --resume.
    The client holds acks for uploads 1-3 when the collector dies at
    the 4th admission; it retries the un-acked 4-6 against the
    resumed process, and the finished collection must equal a clean
    run's bit for bit with exactly 6 reports admitted overall."""
    import subprocess
    from http.client import HTTPConnection

    import numpy as np

    from mastic_tpu.drivers import faults
    from mastic_tpu.drivers.service import encode_upload
    from mastic_tpu.mastic import MasticCount
    from mastic_tpu.net.ingest import MEDIA_TYPE

    serve_py = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "serve.py")
    bits = 2
    m = MasticCount(bits)
    rng = np.random.default_rng(args.replay + 30)
    blobs = []
    for value in [0, 0, 0, 3, 3, 3]:
        alpha = m.vidpf.test_index_from_int(value, bits)
        nonce = bytes(rng.integers(0, 256, m.NONCE_SIZE,
                                   dtype="uint8"))
        rand = bytes(rng.integers(0, 256, m.RAND_SIZE,
                                  dtype="uint8"))
        (ps, shares) = m.shard(b"serve count", (alpha, True), nonce,
                               rand)
        blobs.append(encode_upload(m, (nonce, ps, shares)))

    def spawn(tag: str, fault=None, resume=False, snap_tag=None):
        pf = os.path.join(tmp, f"{tag}.port")
        snap = os.path.join(tmp, f"{snap_tag or tag}.snap")
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        env.pop("MASTIC_FAULTS", None)
        env.pop("MASTIC_NET_SHAPE", None)
        if fault is not None:
            env["MASTIC_FAULTS"] = fault
        cmd = [sys.executable, serve_py, "--reports", "6", "--bits",
               str(bits), "--page-size", "2", "--upload-port", "0",
               "--upload-window", "120", "--port-file", pf,
               "--snapshot", snap]
        if resume:
            cmd.append("--resume")
        proc = subprocess.Popen(cmd, env=env, text=True,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE)
        return (proc, pf, snap)

    def put_all(port: int, send: list) -> list:
        """PUT each blob on a fresh connection; returns the indices
        the client holds a 2xx ack for (the rest are its to
        retry)."""
        acked = []
        for (i, blob) in send:
            try:
                conn = HTTPConnection("127.0.0.1", port, timeout=30)
                conn.request("PUT", "/v1/tenants/count/reports",
                             body=blob,
                             headers={"Content-Type": MEDIA_TYPE})
                resp = conn.getresponse()
                resp.read()
                conn.close()
                if resp.status in (201, 202):
                    acked.append(i)
            except OSError as exc:
                # The collector died mid-upload: stop here and retry
                # the un-acked tail against the resumed process.
                print(f"loadgen: upload {i} un-acked "
                      f"({type(exc).__name__}) — client will retry",
                      file=sys.stderr, flush=True)
                break
        return acked

    def cut_and_drain(port: int) -> None:
        conn = HTTPConnection("127.0.0.1", port, timeout=30)
        conn.request("POST", "/v1/tenants/count/epoch",
                     headers={"Content-Length": "0"})
        conn.getresponse().read()
        conn.request("POST", "/v1/admin/drain",
                     headers={"Content-Length": "0"})
        conn.getresponse().read()
        conn.close()

    def finish(proc, tag: str, expect_rc=0) -> dict:
        (out, err) = proc.communicate(timeout=1500)
        if proc.returncode != expect_rc:
            fail(f"kill9 {tag}: rc={proc.returncode} (wanted "
                 f"{expect_rc}): {err[-1500:]}")
        if expect_rc != 0:
            return {}
        return json.loads(out.strip().splitlines()[-1])

    # Clean run: all six acked, cut, drain.
    (proc, pf, _snap) = spawn("clean")
    port = _wait_port(pf)
    acked = put_all(port, list(enumerate(blobs)))
    if len(acked) != 6:
        proc.kill()
        fail(f"kill9 clean: only {acked} acked")
    cut_and_drain(port)
    clean = finish(proc, "clean")

    # Killed run: the injector kills the collector at the 4th
    # admission; the client keeps acks 0-2.
    (proc, pf, snap) = spawn(
        "killed", fault="kill:party=collector:step=admit:nth=4")
    port = _wait_port(pf)
    acked = put_all(port, list(enumerate(blobs)))
    finish(proc, "killed", expect_rc=faults.KILL_EXIT_CODE)
    if acked != [0, 1, 2]:
        fail(f"kill9 killed: acked {acked}, wanted [0, 1, 2]")
    if not os.path.exists(snap):
        fail("kill9: killed child left no snapshot")

    # Resumed run: retry the un-acked tail, cut, drain.  (Own port
    # file, the KILLED run's snapshot.)
    (proc, pf2, _s) = spawn("resumed", resume=True,
                            snap_tag="killed")
    port = _wait_port(pf2)
    acked = put_all(port, [(i, blobs[i]) for i in (3, 4, 5)])
    if len(acked) != 3:
        proc.kill()
        fail(f"kill9 resume: retries acked {acked}")
    cut_and_drain(port)
    resumed = finish(proc, "resumed")

    if resumed["results"]["count"] != clean["results"]["count"]:
        fail(f"kill9: resumed results diverge: "
             f"{resumed['results']['count']} != "
             f"{clean['results']['count']}")
    admitted = resumed["metrics"]["tenants"]["count"]["counters"][
        "admitted"]
    if admitted != 6:
        fail(f"kill9: {admitted} reports admitted over both lives, "
             f"wanted exactly 6 (lost or duplicated)")
    # Time-to-recover is a first-class metric (ISSUE 18): the resumed
    # collector stamps its WAL recovery attribution and the drill
    # carries it into the BENCH_*/PERF record.
    wal_info = resumed.get("wal") or {}
    if "recovery_wall_ms" not in wal_info:
        fail(f"kill9: resumed run did not stamp WAL recovery "
             f"attribution: {wal_info}")
    return {"clean_result": clean["results"]["count"],
            "resumed_result": resumed["results"]["count"],
            "admitted_total": admitted,
            "recovery_wall_ms": wal_info["recovery_wall_ms"],
            "replayed_records": wal_info.get("replayed_records", 0),
            "bit_identical": True}


class _SnapshotSettler:
    """The r16 durability discipline as a persist callback, for the
    §14 baseline: an ack is released only after a FULL service
    snapshot (serialize + fsync + rename + fsync(dir)) covering it
    lands.  Generously batched — one settle releases every waiter
    that arrived while the previous snapshot was writing, the exact
    analogue of the WAL's group commit — so the measured gap is the
    cost of serializing O(state) per settle vs appending O(record)."""

    def __init__(self, svc, path: str):
        import threading

        self.svc = svc
        self.path = path
        self.snapshot_bytes = 0
        self.settles = 0
        self._mu = threading.Lock()
        self._waiters: list = []
        self._closed = False
        self._thread = threading.Thread(target=self._loop,
                                        daemon=True,
                                        name="snapshot-settler")
        self._thread.start()

    def persist(self, tenant: str, body: bytes) -> None:
        import threading

        ev = threading.Event()
        with self._mu:
            self._waiters.append(ev)
        if not ev.wait(60.0):
            raise RuntimeError("snapshot settle timed out")

    def _loop(self) -> None:
        from mastic_tpu.drivers.wal import fsync_dir

        while True:
            with self._mu:
                if self._closed:
                    for ev in self._waiters:
                        ev.set()
                    return
                batch = self._waiters
                self._waiters = []
            if not batch:
                time.sleep(0.0005)
                continue
            data = self.svc.to_bytes()
            tmp = self.path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            fsync_dir(os.path.dirname(os.path.abspath(self.path)))
            self.snapshot_bytes = len(data)
            self.settles += 1
            for ev in batch:
                ev.set()

    def close(self) -> None:
        with self._mu:
            self._closed = True
        self._thread.join(timeout=10.0)


def run_wal_bench(args) -> None:
    """The measured cost of durability (ISSUE 18, PERF.md §14): the
    SAME HTTP admission path — real sockets, worker clients, valid
    blobs — under three persistence disciplines:

      1. ``snapshot_before_ack`` — the r16 baseline (a full durable
         service snapshot covers every ack), batched as generously
         as the WAL's group commit;
      2. ``wal_always`` — one fsync per record, the latency floor;
      3. ``wal_group``  — the shipped default (``group:2`` ms).

    Prints one JSON line (committed as BENCH_WAL.json) with the
    admission rate per mode, the WAL modes' p50/p99 fsync-wait, and
    the group-vs-snapshot speedup; FAILS unless group commit admits
    at least 5x the snapshot-before-ack rate."""
    import shutil
    import tempfile
    import threading
    from http.client import HTTPConnection

    from mastic_tpu.drivers.wal import AdmissionWal, WalConfig
    from mastic_tpu.net.admission import NetConfig
    from mastic_tpu.net.ingest import MEDIA_TYPE, UploadFront

    t_start = time.time()
    reports = args.wal_reports
    workers = args.wal_workers
    (_svc0, tenants) = build_service(bits=2, max_buffered=10 ** 6,
                                     ingest_threads=0,
                                     ingest_queue=256)
    pool = build_pools(tenants, 2, pool=64,
                       replay=args.replay)["count"]["valid"]
    tmp = tempfile.mkdtemp(prefix="mastic-wal-bench-")

    def drive(front) -> tuple:
        """`reports` PUTs over `workers` keep-alive connections;
        returns (acked, wall_s).  Any non-2xx fails the bench — this
        path must admit everything, or the rates compare nothing."""
        next_i = [0]
        mu = threading.Lock()
        acked = [0]
        errors: list = []

        def worker() -> None:
            conn = HTTPConnection("127.0.0.1", front.port,
                                  timeout=30)
            try:
                while True:
                    with mu:
                        i = next_i[0]
                        if i >= reports or errors:
                            return
                        next_i[0] = i + 1
                    blob = pool[i % len(pool)]
                    # A dropped keep-alive or accept-backlog reset is
                    # the client's to retry (the un-acked upload is
                    # at-least-once by contract); only a persistent
                    # transport failure fails the bench.
                    status = None
                    for attempt in range(3):
                        try:
                            conn.request(
                                "PUT", "/v1/tenants/count/reports",
                                body=blob,
                                headers={"Content-Type": MEDIA_TYPE})
                            resp = conn.getresponse()
                            resp.read()
                            status = resp.status
                            break
                        except OSError:
                            conn.close()
                            time.sleep(0.01 * (attempt + 1))
                            conn = HTTPConnection(
                                "127.0.0.1", front.port, timeout=30)
                    if status is None:
                        errors.append(f"transport error on {i}")
                        return
                    if status not in (201, 202):
                        errors.append(f"upload {i}: {status}")
                        return
                    with mu:
                        acked[0] += 1
            finally:
                conn.close()

        threads = [threading.Thread(target=worker)
                   for _ in range(workers)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.monotonic() - t0
        if errors:
            fail(f"wal bench: {errors[0]}")
        return (acked[0], wall)

    def fresh_front(persist):
        (svc, _t) = build_service(bits=2, max_buffered=10 ** 6,
                                  ingest_threads=0, ingest_queue=256)
        front = UploadFront(
            svc, config=NetConfig(max_connections=256,
                                  trust_forwarded=True),
            persist=persist).start()
        return (svc, front)

    modes = {}

    # 1. Snapshot-before-ack (the r16 discipline).
    (svc, front) = fresh_front(None)
    settler = _SnapshotSettler(svc, os.path.join(tmp, "base.snap"))
    front._persist = settler.persist
    (acked, wall) = drive(front)
    front.stop()
    settler.close()
    if acked != reports:
        fail(f"wal bench snapshot: {acked}/{reports} acked")
    modes["snapshot_before_ack"] = {
        "acked": acked, "wall_s": round(wall, 3),
        "rate_rps": round(acked / wall, 1),
        "settles": settler.settles,
        "snapshot_bytes_final": settler.snapshot_bytes}

    # 2 + 3. The WAL disciplines.
    for (key, cfg) in (
            ("wal_always", WalConfig(fsync="always")),
            ("wal_group", WalConfig(fsync="group", group_ms=2.0))):
        wal = AdmissionWal(os.path.join(tmp, key), config=cfg)
        (svc, front) = fresh_front(wal.append_report)
        (acked, wall) = drive(front)
        front.stop()
        stats = wal.stats()
        wal.close()
        if acked != reports:
            fail(f"wal bench {key}: {acked}/{reports} acked")
        modes[key] = {
            "acked": acked, "wall_s": round(wall, 3),
            "rate_rps": round(acked / wall, 1),
            "fsync": cfg.fsync,
            "fsync_wait_ms_p50": round(
                stats["fsync_wait_ms_p50"], 3),
            "fsync_wait_ms_p99": round(
                stats["fsync_wait_ms_p99"], 3),
            "appends": stats["appends"],
            "segments": stats["segments"]}
        if key == "wal_group":
            modes[key]["group_ms"] = 2.0

    shutil.rmtree(tmp, ignore_errors=True)
    speedup = (modes["wal_group"]["rate_rps"]
               / modes["snapshot_before_ack"]["rate_rps"])
    out = {"mode": "wal-bench", "reports": reports,
           "workers": workers, "modes": modes,
           "speedup_group_vs_snapshot": round(speedup, 2),
           "wall_seconds": round(time.time() - t_start, 1),
           "ok": True}
    line = json.dumps(out)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    if speedup < 5.0:
        fail(f"wal bench: group-commit admission rate is only "
             f"{speedup:.2f}x the snapshot-before-ack baseline "
             f"(acceptance: >= 5x)")


def run_smoke(args) -> None:
    import tempfile

    t0 = time.time()
    out = {"mode": "loadgen-smoke",
           "slo": phase_slo(args),
           "knee": phase_knee(args),
           "ratelimit": phase_ratelimit(args)}
    if args.skip_drill:
        out["kill9"] = {"skipped": True}
    else:
        tmp = tempfile.mkdtemp(prefix="mastic_net_drill_")
        out["kill9"] = run_upload_drill(args, tmp)
    out["wall_seconds"] = round(time.time() - t0, 1)
    out["ok"] = True
    line = json.dumps(out)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")


def run_load(args) -> None:
    """One load phase (the `serve-load` cell): self-hosted by
    default; with --target, drive a running `tools/serve.py
    --upload-port` endpoint's demo ``count`` tenant instead (blobs
    are built for its ctx; accounting is then response-side only —
    the endpoint's own /metrics has the server ledger)."""
    from mastic_tpu.net.loadgen import (LoadGenerator, LoadProfile,
                                        build_blob_pool, malform)

    t0 = time.time()
    profile = LoadProfile(
        clients=args.clients, duration_s=args.duration,
        rate=args.rate, burst_factor=args.burst_factor,
        malformed_frac=args.malformed_frac, zipf_s=args.zipf,
        workers=args.workers, replay=args.replay)
    if args.target:
        import urllib.parse

        import numpy as np

        from mastic_tpu.mastic import MasticCount

        u = urllib.parse.urlparse(args.target)
        m = MasticCount(args.bits)
        rng = np.random.default_rng(args.replay + 1)
        valid = build_blob_pool(m, b"serve count", 64, args.bits,
                                replay=args.replay)
        pools = {"count": {"valid": valid,
                           "malformed": [malform(b, rng)
                                         for b in valid[:16]]}}
        gen = LoadGenerator(u.hostname, u.port, profile, pools)
        rec = gen.run()
        svc = None
    else:
        from mastic_tpu.net.admission import NetConfig
        from mastic_tpu.net.ingest import UploadFront

        profile.tenant_weights = {"count": 0.8, "attrs": 0.2}
        (svc, tenants) = build_service(
            bits=args.bits, max_buffered=10 ** 6,
            ingest_threads=args.ingest_threads,
            ingest_queue=args.ingest_queue)
        pools = build_pools(tenants, args.bits, pool=64,
                            replay=args.replay)
        front = UploadFront(
            svc, config=NetConfig(max_connections=256,
                                  trust_forwarded=True)).start()
        rec = run_phase(svc, front, profile, pools)
        front.stop()
        check_accounting(rec, "load")
    p99 = rec["latency_ms"]["p99"]
    rec.update({"mode": "serve-load", "slo_p99_ms": args.slo_p99_ms,
                "slo_held": p99 is not None and p99 <= args.slo_p99_ms,
                "target": args.target,
                "wall_seconds": round(time.time() - t0, 1)})
    rec["ok"] = bool(rec["slo_held"])
    line = json.dumps(rec)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    if not rec["ok"]:
        fail(f"serve-load: p99 {p99} ms over the {args.slo_p99_ms} "
             f"ms SLO")


def main() -> None:
    parser = argparse.ArgumentParser(
        description="closed-loop load generator for the upload front "
                    "(USAGE.md 'Network front')")
    parser.add_argument("--smoke", action="store_true",
                        help="the make net-smoke gate (four phases)")
    parser.add_argument("--skip-drill", action="store_true",
                        help="skip the kill-9 subprocess drill "
                             "inside --smoke (fast local iteration)")
    parser.add_argument("--self", dest="selfhost", action="store_true",
                        help="self-host the service + front "
                             "(default)")
    parser.add_argument("--target", type=str, default=None,
                        help="drive an external endpoint instead")
    parser.add_argument("--clients", type=int, default=100_000,
                        help="simulated client population")
    parser.add_argument("--bits", type=int, default=2,
                        help="tenant tree depth for blob building")
    parser.add_argument("--duration", type=float, default=6.0)
    parser.add_argument("--rate", type=float, default=250.0,
                        help="offered arrivals/s outside bursts")
    parser.add_argument("--burst-factor", type=float, default=3.0)
    parser.add_argument("--malformed-frac", type=float, default=0.03)
    parser.add_argument("--zipf", type=float, default=1.2)
    parser.add_argument("--workers", type=int, default=6)
    parser.add_argument("--ingest-threads", type=int, default=0)
    parser.add_argument("--ingest-queue", type=int, default=256)
    parser.add_argument("--slo-p99-ms", type=float, default=250.0,
                        help="the stated admission-latency SLO the "
                             "run must hold")
    parser.add_argument("--seed", dest="replay", type=int,
                        default=0, help="deterministic replay index")
    parser.add_argument("--wal-bench", action="store_true",
                        help="measure the durability disciplines "
                             "head to head (snapshot-before-ack vs "
                             "WAL always vs WAL group commit) over "
                             "the real HTTP path; PERF.md §14")
    parser.add_argument("--wal-reports", type=int, default=20000,
                        help="uploads per --wal-bench mode — the "
                             "baseline's per-settle cost is O(state), "
                             "so the measured gap grows with this "
                             "(PERF.md §14 quotes the curve)")
    parser.add_argument("--wal-workers", type=int, default=32,
                        help="concurrent clients per --wal-bench "
                             "mode")
    parser.add_argument("--out", type=str, default=None)
    args = parser.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if args.wal_bench:
        run_wal_bench(args)
    elif args.smoke:
        run_smoke(args)
    else:
        run_load(args)


if __name__ == "__main__":
    main()
