"""Driver tests: heavy hitters vs the functional oracle, attribute
metrics, and the communication report vs the measured size formulas
(SURVEY.md §2.4)."""

import pytest

pytestmark = pytest.mark.slow


from mastic_tpu import MasticCount, MasticSum
from mastic_tpu.drivers import (aggregate_by_attribute,
                                communication_report,
                                compute_heavy_hitters, get_threshold,
                                get_reports_from_measurements,
                                hash_attribute)
from mastic_tpu.oracle import weighted_heavy_hitters


def test_heavy_hitters_matches_oracle():
    bits = 4
    mastic = MasticCount(bits)
    ctx = b"hh driver test"
    values = [0b1001, 0b0000, 0b0000, 0b0000, 0b1001, 0b0000, 0b1100,
              0b0011, 0b1111, 0b1111]
    weights = [1, 1, 0, 1, 1, 1, 1, 1, 0, 1]
    measurements = [
        (mastic.vidpf.test_index_from_int(v, bits), w)
        for (v, w) in zip(values, weights)
    ]
    reports = get_reports_from_measurements(mastic, ctx, measurements)
    got = compute_heavy_hitters(mastic, ctx, {"default": 2}, reports)
    want = weighted_heavy_hitters(measurements, 2, bits)
    assert sorted(got) == want
    assert want  # the example is non-trivial


def test_heavy_hitters_per_prefix_thresholds():
    bits = 3
    mastic = MasticCount(bits)
    ctx = b"hh thresholds"
    values = [0b000, 0b000, 0b001, 0b100, 0b101, 0b110]
    measurements = [
        (mastic.vidpf.test_index_from_int(v, bits), 1) for v in values
    ]
    reports = get_reports_from_measurements(mastic, ctx, measurements)
    # Default threshold 2; subtree under (True,) uses threshold 1.
    thresholds = {"default": 2, (True,): 1}
    got = compute_heavy_hitters(mastic, ctx, thresholds, reports)
    assert sorted(got) == [
        (False, False, False),
        (True, False, False),
        (True, False, True),
        (True, True, False),
    ]
    assert get_threshold(thresholds, (True, False, False)) == 1
    assert get_threshold(thresholds, (False, False, True)) == 2


def test_attribute_metrics():
    mastic = MasticSum(8, 3)
    ctx = b"attr metrics"
    votes = [("United States", 1), ("Greece", 1), ("United States", 2),
             ("Greece", 0), ("United States", 0), ("India", 1),
             ("Greece", 0), ("United States", 1), ("Greece", 1),
             ("Greece", 3), ("Greece", 1)]
    reports = get_reports_from_measurements(
        mastic, ctx,
        [(hash_attribute(mastic, a), v) for (a, v) in votes])
    result = aggregate_by_attribute(
        mastic, ctx, ["Greece", "Mexico", "United States"], reports)
    assert result == [("Greece", 6), ("Mexico", 0),
                      ("United States", 4)]

    # The chunked streaming path (11 reports -> 4+4+3) is
    # bit-identical, with per-round metrics.
    from mastic_tpu.common import gen_rand
    vk = gen_rand(mastic.VERIFY_KEY_SIZE)
    (m_full, m_chunked) = ([], [])
    full = aggregate_by_attribute(
        mastic, ctx, ["Greece", "Mexico", "United States"], reports,
        verify_key=vk, metrics_out=m_full)
    chunked = aggregate_by_attribute(
        mastic, ctx, ["Greece", "Mexico", "United States"], reports,
        verify_key=vk, metrics_out=m_chunked, chunk_size=4)
    assert full == chunked == result
    assert m_full[0].accepted == m_chunked[0].accepted == len(reports)
    assert m_chunked[0].extra["chunk_size"] == 4
    assert m_full[0].bytes_upload == m_chunked[0].bytes_upload


def test_communication_report_matches_formulas():
    sizes = communication_report(print_fn=lambda *_: None)
    # Public-share formula: ceil(2b/8) + b*(16 + v*elem + 32)
    # (SURVEY.md §2.4, verified against the conformance vectors).
    count = sizes["MasticCount(256)"]
    assert count["public_share"] == 64 + 256 * (16 + 2 * 8 + 32)
    assert count["leader_share"] == 16 + 5 * 8
    assert count["helper_share"] == 16 + 32
    hist = sizes["MasticHistogram(32, 100, 10)"]
    assert hist["public_share"] == 8 + 32 * (16 + 101 * 16 + 32)
    assert hist["helper_share"] == 16 + 32 + 32


def test_communication_report_comparison_story():
    """The reference's headline comparisons (examples.py:263-364),
    reproduced from published vdaf-13 constants (SURVEY.md §2.2)."""
    sizes = communication_report(print_fn=lambda *_: None)
    poplar = sizes["Poplar1(256)"]
    # vdaf-13 §8 structure: 64 ctrl bytes + 256 seed CWs + 255 inner +
    # 1 leaf payload CW; leader carries the explicit (a, b, c) sketch
    # correlation.
    assert poplar["public_share"] == 64 + 256 * 16 + 255 * 16 + 64
    assert poplar["leader_share"] == 48 + 3 * 255 * 8 + 3 * 32
    assert poplar["upload"] == 8304 + 6264 + 48
    # Mastic's upload is within ~15% of Poplar1's while also carrying
    # a weight and needing one prep round instead of two.
    ratio = sizes["mastic_count_vs_poplar1_upload"]
    assert 1.0 < ratio < 1.2

    prio3 = sizes["Prio3Histogram(10000, 100)"]
    # Histogram(10000, 100): 100 Mul-gadget calls -> PROOF_LEN
    # 2*100 + 2*(next_pow_2(101) - 1) + 1 = 455 over Field128.
    assert prio3["leader_share"] == (10000 + 455) * 16 + 32
    assert prio3["upload"] == 64 + 167312 + 64
    # Attribute-metrics mode: Mastic's upload is ~3x smaller than the
    # flat Prio3 histogram over the product space.
    assert sizes["prio3_vs_mastic_histogram_upload"] > 3.0
