"""OB001 bad fixture: bare prints in library code — stdout AND
stderr are both invisible to the telemetry layer."""

import sys


def noisy_round(level: int) -> int:
    print(f"starting level {level}")                    # OB001
    result = level * 2
    print(f"level done: {result}", file=sys.stderr)     # OB001 too:
    # stderr is just as unscrapable as stdout
    return result
