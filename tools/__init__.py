"""Repo tooling (lint gate, static analyzer, northstar driver).

A package so `python -m tools.analysis` works; the scripts themselves
stay directly runnable (`python tools/lint.py`).
"""
