"""EV001 clean: the recv lives in a selector callback — the loop
dispatched it only after select() proved the fd ready."""
import selectors


def on_readable(sock):
    return sock.recv(4096)


def loop(sel, sock):
    sel.register(sock, selectors.EVENT_READ, on_readable)
    while True:
        for (key, _mask) in sel.select():
            key.data(key.fileobj)
