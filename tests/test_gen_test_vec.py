"""The deterministic generator must re-emit every reference vector
byte-for-byte (JSON formatting included) — full wire fidelity."""

import os

import pytest

from mastic_tpu.gen_test_vec import (all_test_vecs, gen_test_vec,
                                     render_test_vec)

REF_DIR = os.environ.get("MASTIC_TEST_VEC",
                         "/root/reference/test_vec/mastic")

CONFIGS = all_test_vecs()


@pytest.mark.parametrize("filename,mastic,agg_param,measurements",
                         CONFIGS, ids=[c[0] for c in CONFIGS])
def test_regenerates_reference_vector(filename, mastic, agg_param,
                                      measurements):
    path = os.path.join(REF_DIR, filename)
    if not os.path.exists(path):
        pytest.skip(f"reference vectors not available at {REF_DIR}")
    with open(path) as f:
        expected = f.read()
    rendered = render_test_vec(
        gen_test_vec(mastic, agg_param, b"some application",
                     measurements))
    assert rendered == expected
