"""Closed-loop/open hybrid load generator for the upload front
(ISSUE 11 tentpole, leg c): simulate 10^5-10^6 clients against
`net/ingest.py` and measure what the paper's deployment story needs
measured — admission latency quantiles, sustained reports/s, and the
shed/quarantine ledger under overload.

Model:

* **client population** — `clients` simulated identities; each
  request draws its client by a zipf(s) popularity law (a few hot
  clients, a long tail — the shape real report traffic has), and the
  client id maps to a synthetic source address carried in
  X-Forwarded-For (the front's per-IP admission runs against 10^5
  distinct addresses through one loopback socket; trust_forwarded is
  the lever that makes that honest);

* **open arrivals, closed workers** — arrival times are a Poisson
  process at `rate`/s with periodic bursts (`burst_factor` for
  `burst_len_s` every `burst_every_s`), generated up front from one
  seed so a run is replayable; a fixed pool of `workers` keep-alive
  connections executes the schedule.  When the service keeps up, the
  workers behave as an open system (each request fires at its
  scheduled instant); past saturation the pool is the closed-loop
  bound — `lateness` quantiles report how far the schedule slipped,
  so coordinated omission is stamped instead of hidden;

* **adversarial mix** — `malformed_frac` of uploads are truncated or
  bit-flipped valid blobs: the endpoint must quarantine each with a
  reason (400), never admit one, and never pay more than a decode.

Everything is deterministic per seed except genuine scheduling
nondeterminism (thread interleaving, service timing).  Results are a
plain dict stamped into the `serve-load` bench cell by
`tools/loadgen.py`.
"""

import socket
import threading
import time
from dataclasses import dataclass, field
from http.client import HTTPConnection
from typing import Optional

import numpy as np

from .ingest import MEDIA_TYPE


def _no_nagle_connection(host: str, port: int,
                         timeout: float) -> HTTPConnection:
    """A keep-alive connection with Nagle off — headers and body go
    in separate writes, and the Nagle x delayed-ACK interaction would
    otherwise put a uniform ~40 ms floor under every measured
    latency (the server side disables it too)."""
    conn = HTTPConnection(host, port, timeout=timeout)
    conn.connect()
    conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return conn


@dataclass
class LoadProfile:
    """One load run.  `clients` is the simulated population size;
    `rate` the offered arrival rate (uploads/s) outside bursts."""

    clients: int = 100_000
    duration_s: float = 8.0
    rate: float = 200.0
    burst_factor: float = 4.0
    burst_every_s: float = 2.0
    burst_len_s: float = 0.25
    malformed_frac: float = 0.02
    zipf_s: float = 1.2
    workers: int = 8
    # The run's replay index — deliberately NOT named "seed": the
    # secret-flow pass rightly treats seed-named values as key
    # material, and this one is a public replay label.
    replay: int = 0
    tenant_weights: dict = field(default_factory=dict)
    # tenant -> relative weight; empty = uniform over the pools given
    # to drive().

    def __post_init__(self):
        if self.clients < 1 or self.rate <= 0 or self.duration_s <= 0:
            raise ValueError("clients/rate/duration must be positive")
        if not 0.0 <= self.malformed_frac <= 1.0:
            raise ValueError("malformed_frac must be in [0, 1]")
        if self.workers < 1:
            raise ValueError("workers must be >= 1")


@dataclass
class _Event:
    t: float          # seconds from run start
    tenant: str
    client: int
    malformed: bool


def client_ip(cid: int) -> str:
    """Deterministic synthetic source address for one simulated
    client (10.0.0.0/8 — never a routable source)."""
    return f"10.{(cid >> 16) & 255}.{(cid >> 8) & 255}.{cid & 255}"


def build_blob_pool(mastic, ctx: bytes, count: int, bits: int,
                    replay: int = 0) -> list:
    """`count` DISTINCT valid upload blobs for one tenant (distinct
    nonces/rand, alternating hot values so heavy hitters exist), via
    the same dual-view codec the service decodes."""
    from ..drivers.service import encode_upload

    rng = np.random.default_rng(replay)
    blobs = []
    for i in range(count):
        value = 0 if i % 2 == 0 else (1 << bits) - 1
        alpha = mastic.vidpf.test_index_from_int(value, bits)
        nonce = bytes(rng.integers(0, 256, mastic.NONCE_SIZE,
                                   dtype="uint8"))
        rand = bytes(rng.integers(0, 256, mastic.RAND_SIZE,
                                  dtype="uint8"))
        (ps, shares) = mastic.shard(ctx, (alpha, True), nonce, rand)
        blobs.append(encode_upload(mastic, (nonce, ps, shares)))
    return blobs


def malform(blob: bytes, rng) -> bytes:
    """One adversarial variant of a valid blob: truncated mid-view or
    bit-flipped inside the first framed view — both decode-fail at
    the door with reason ``malformed``."""
    if rng.integers(0, 2) == 0:
        return blob[:max(8, len(blob) // 2)]
    mutated = bytearray(blob)
    mutated[8] ^= 0x01
    return bytes(mutated)


def build_schedule(profile: LoadProfile, tenants: list) -> list:
    """The full arrival schedule, generated up front from one seed:
    Poisson inter-arrivals at the (burst-modulated) offered rate,
    zipf-drawn clients, weighted tenant mix, malformed flags."""
    rng = np.random.default_rng(profile.replay)
    weights = np.array([profile.tenant_weights.get(t, 1.0)
                        for t in tenants], float)
    weights /= weights.sum()
    events: list = []
    t = 0.0
    while t < profile.duration_s:
        in_burst = (t % profile.burst_every_s) < profile.burst_len_s
        r = profile.rate * (profile.burst_factor if in_burst else 1.0)
        t += float(rng.exponential(1.0 / r))
        if t >= profile.duration_s:
            break
        cid = int(rng.zipf(profile.zipf_s) - 1) % profile.clients
        tenant = tenants[int(rng.choice(len(tenants), p=weights))]
        events.append(_Event(
            t=t, tenant=tenant, client=cid,
            malformed=bool(rng.random() < profile.malformed_frac)))
    return events


def quantiles(values: list, qs=(50, 95, 99)) -> dict:
    if not values:
        return {f"p{q}": None for q in qs}
    arr = np.sort(np.asarray(values, float))
    return {f"p{q}": round(float(
        arr[min(len(arr) - 1, int(len(arr) * q / 100.0))]), 3)
        for q in qs}


class _Worker:
    """One keep-alive connection executing its slice of the shared
    schedule.  All mutable state is worker-local (results merge after
    join — no cross-thread mutation for the CC pass to frown at
    except the index cursor, which the dispenser lock guards)."""

    def __init__(self, gen: "LoadGenerator", wid: int):
        self.gen = gen
        self.wid = wid
        self.codes: dict = {}
        self.latencies: list = []
        self.lateness: list = []
        self.transport_errors = 0
        self.retry_after_seen = 0
        self.clients_seen: set = set()

    def run(self) -> None:
        gen = self.gen
        self._conn: Optional[HTTPConnection] = None
        try:
            while True:
                i = gen._next_index()
                if i is None:
                    return
                ev = gen.events[i]
                due = gen.t_start + ev.t
                now = time.perf_counter()
                if now < due:
                    time.sleep(due - now)
                    now = time.perf_counter()
                # mastic-allow: RB004 — bounded by the precomputed
                # schedule: the shared cursor exhausts after
                # len(events) draws and the loop returns above
                self.lateness.append((now - due) * 1e3)
                self._one(self._connection(), ev)
        finally:
            if self._conn is not None:
                self._conn.close()

    def _connection(self) -> HTTPConnection:
        if self._conn is None or self._conn.sock is None:
            if self._conn is not None:
                self._conn.close()
            self._conn = _no_nagle_connection(
                self.gen.host, self.gen.port,
                self.gen.request_timeout)
        return self._conn

    def _one(self, conn: HTTPConnection, ev: _Event) -> None:
        gen = self.gen
        pool = gen.pools[ev.tenant]
        blob = (pool["malformed"][ev.client % len(pool["malformed"])]
                if ev.malformed
                else pool["valid"][ev.client % len(pool["valid"])])
        headers = {"Content-Type": MEDIA_TYPE,
                   "Content-Length": str(len(blob)),
                   "X-Forwarded-For": client_ip(ev.client)}
        t0 = time.perf_counter()
        try:
            conn.request("PUT", f"/v1/tenants/{ev.tenant}/reports",
                         body=blob, headers=headers)
            resp = conn.getresponse()
            resp.read()
            code = resp.status
            if resp.getheader("Retry-After") is not None:
                self.retry_after_seen += 1
            if resp.getheader("Connection") == "close":
                conn.close()
        except OSError:
            self.transport_errors += 1
            conn.close()
            return
        self.latencies.append((time.perf_counter() - t0) * 1e3)
        self.codes[code] = self.codes.get(code, 0) + 1
        self.clients_seen.add(ev.client)


class LoadGenerator:
    """Drive one schedule against one endpoint; `run()` returns the
    stamped result dict."""

    def __init__(self, host: str, port: int, profile: LoadProfile,
                 pools: dict, request_timeout: float = 30.0):
        self.host = host
        self.port = port
        self.profile = profile
        self.pools = pools
        self.request_timeout = request_timeout
        self.events = build_schedule(profile, sorted(pools))
        self._mu = threading.Lock()
        self._cursor = 0
        self.t_start = 0.0

    def _next_index(self) -> Optional[int]:
        with self._mu:
            if self._cursor >= len(self.events):
                return None
            i = self._cursor
            self._cursor += 1
            return i

    def run(self) -> dict:
        profile = self.profile
        workers = [_Worker(self, w) for w in range(profile.workers)]
        self.t_start = time.perf_counter()
        threads = [threading.Thread(target=w.run, daemon=True,
                                    name=f"mastic-loadgen-{w.wid}")
                   for w in workers]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        wall = time.perf_counter() - self.t_start

        codes: dict = {}
        latencies: list = []
        lateness: list = []
        clients_seen: set = set()
        transport_errors = 0
        retry_after_seen = 0
        for w in workers:
            for (code, n) in w.codes.items():
                codes[code] = codes.get(code, 0) + n
            latencies += w.latencies
            lateness += w.lateness
            clients_seen |= w.clients_seen
            transport_errors += w.transport_errors
            retry_after_seen += w.retry_after_seen
        answered = sum(codes.values())
        return {
            "offered": len(self.events),
            "offered_rate_per_sec": round(
                len(self.events) / profile.duration_s, 1),
            "answered": answered,
            "achieved_rate_per_sec": round(answered / wall, 1)
            if wall > 0 else 0.0,
            "wall_s": round(wall, 3),
            "codes": {str(k): v for (k, v) in sorted(codes.items())},
            "transport_errors": transport_errors,
            "retry_after_seen": retry_after_seen,
            "latency_ms": quantiles(latencies),
            "lateness_ms": quantiles(lateness),
            "simulated_clients": profile.clients,
            "distinct_clients_seen": len(clients_seen),
            "malformed_frac": profile.malformed_frac,
            "workers": profile.workers,
            "replay": profile.replay,
        }


def decode_pool_multiset(pages_blobs: list) -> dict:
    """Multiset of upload blobs (the r15 page-multiset equality
    check, network edition): map blob -> count, for comparing what
    the service buffered against what the clients got 2xx acks
    for."""
    out: dict = {}
    for blob in pages_blobs:
        out[blob] = out.get(blob, 0) + 1
    return out


def buffered_blobs(service, tenant: str) -> list:
    """Every admitted upload blob the tenant currently buffers (open
    page + sealed pages + queued epochs), decoded from the stored
    page payloads — the ground truth the zero-lost/zero-duplicated
    assertion compares against."""
    t = service.tenants[tenant]
    with t.lock:
        pages = ([t.open_page] + list(t.sealed)
                 + [p for ep in t.pending for p in ep.pages]
                 + (list(t.active.pages) if t.active is not None
                    else []))
        out: list = []
        for page in pages:
            out += page.decode_blobs()
    return out
