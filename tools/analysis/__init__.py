"""Static analyzer for the trace-safety / dtype / secret-flow /
Pallas / robustness / observability / concurrency invariants that
make this reproduction's bit-exact crypto survive jit + Pallas and
its collector service survive a second thread (run via
`make analyze`; part of `make ci`).

Nine passes, each with stable rule IDs, each scoped to the layer
whose contract it checks:

  tracesafe   TS001-TS004   mastic_tpu/ops/, backend/, flp/flp_jax.py
  dtypes      DT001-DT003   mastic_tpu/ops/ (field/AES/Keccak kernels)
  secretflow  SF001-SF002   vidpf.py, mastic.py, aes.py, xof.py
              SF003-SF005   whole-program: drivers/, obs/, net/,
                            metrics.py, tools/serve.py,
                            tools/loadgen.py
  pallasck    PL001-PL004   any file calling pallas_call
  robustness  RB001-RB005   mastic_tpu/drivers/ + mastic_tpu/net/
                            + tools/serve.py + tools/loadgen.py
  observability OB001       mastic_tpu/ library code
  concurrency CC001-CC004   whole-program: drivers/, obs/,
                            tools/serve.py (threads + locks)
  lifetime    RL001-RL005   CFG path-sensitive resource lifetimes:
                            mastic_tpu/net/ + session/party drivers
                            + tools/{party,serve,loadgen}.py
  evloop      EV001-EV003   whole-program: blocking calls / send
                            loops in non-blocking (selector)
                            contexts, same scope as lifetime

The lifetime pass runs on the CFG engine (`cfg.py`): every function
is lowered to basic blocks with explicit raise edges out of every
call, and per-resource open/closed facts are pushed along all paths
to fixpoint (ISSUE 17 — the static gate the event-loop ingest
rewrite lands on).

plus the suppression meta-rules AL001 (mastic-allow without a written
justification) and AL002 (mastic-allow that silences nothing), and
XX000 (file does not parse).

The whole-program passes (concurrency, secretflow's SF300 series)
consume one `callgraph.Program` built from the SAME parsed ASTs the
per-file passes read: every source file is parsed exactly once per
run and the `FileInfo`s are threaded through all passes (ISSUE 8
satellite — previously each invocation could re-walk the tree per
pass).  They resolve best when run over the full default file set;
a partial path list analyzes a partial program.

Findings are suppressed inline with `# mastic-allow: <ID>[, <ID>] —
reason`, on the flagged line or as a comment line directly above the
flagged statement.  There are no file-level exclusions: every
accepted risk is written down where the code is, and the TOTAL is
budgeted — `--stats` prints per-rule suppression counts and fails
when the count exceeds the committed baseline
(tools/analysis/allow_budget.json), so accepted risk only grows via
an explicit baseline bump in the diff.  `--sarif PATH` writes the
findings (suppressed ones included, with their justifications) as a
SARIF 2.1.0 log for CI artifact upload.

See USAGE.md ("Static analysis") for the rule table and workflow.
"""

import hashlib
import json
import os
import pathlib
import time

from . import (callgraph, concurrency, dtypes, evloop, lifetime,
               observability, pallasck, robustness, secretflow,
               tracesafe)
from .core import REPO, Finding, load_file
from .sarif import to_sarif

PASSES = (tracesafe, dtypes, secretflow, pallasck, robustness,
          observability, concurrency, lifetime, evloop)

DEFAULT_ROOTS = ("mastic_tpu", "tools", "bench.py")

BUDGET_FILE = pathlib.Path(__file__).parent / "allow_budget.json"

CACHE_DIR = REPO / "artifacts" / "analysis-cache"


def _analyzer_fingerprint() -> bytes:
    """SHA-256 over the analyzer's own sources: any change to a pass,
    the CFG engine or the call-graph model invalidates every cached
    entry (no manual version bumps to forget)."""
    h = hashlib.sha256()
    for path in sorted(pathlib.Path(__file__).parent.glob("*.py")):
        h.update(path.name.encode())
        h.update(path.read_bytes())
    return h.digest()


class AnalysisCache:
    """Content-addressed result cache (ISSUE 17 satellite).  Per-file
    pass results are keyed by content SHA-256 + analyzer fingerprint
    + run flags; the whole-program layer (call graph, concurrency,
    SF300s, lifetime, evloop) is a property of the file SET, so it is
    cached as one entry keyed over every file's digest — touch any
    file and only the interprocedural work plus that file rerun.  A
    fully warm run is parse + suppression matching only.  Entries are
    plain JSON under artifacts/analysis-cache/ (override:
    MASTIC_ANALYSIS_CACHE_DIR)."""

    def __init__(self, root=None):
        self.root = pathlib.Path(
            root or os.environ.get("MASTIC_ANALYSIS_CACHE_DIR",
                                   CACHE_DIR))
        self.hits = 0
        self.misses = 0
        self.program_hit = False
        self._fp = _analyzer_fingerprint()

    def key(self, info, pass_names, force_scope: bool) -> str:
        h = hashlib.sha256()
        h.update(self._fp)
        h.update(hashlib.sha256(info.src.encode()).digest())
        h.update(info.rel.encode())
        h.update(repr((sorted(pass_names), force_scope)).encode())
        return h.hexdigest()

    def program_key(self, infos, pass_names,
                    force_scope: bool) -> str:
        """One key over the whole file SET: the interprocedural
        results depend on every file, so any content change anywhere
        invalidates them (and an unchanged tree skips the call-graph
        build entirely)."""
        h = hashlib.sha256()
        h.update(b"whole-program")
        h.update(self._fp)
        for info in sorted(infos, key=lambda i: i.rel):
            h.update(info.rel.encode())
            h.update(hashlib.sha256(info.src.encode()).digest())
        h.update(repr((sorted(pass_names), force_scope)).encode())
        return h.hexdigest()

    def get(self, key: str):
        try:
            return json.loads(
                (self.root / f"{key}.json").read_text())
        except (OSError, ValueError):
            return None

    def put(self, key: str, rows: list) -> None:
        try:
            self.root.mkdir(parents=True, exist_ok=True)
            tmp = self.root / f".{key}.tmp"
            tmp.write_text(json.dumps(rows))
            tmp.replace(self.root / f"{key}.json")
        except OSError:
            pass    # a read-only checkout just runs cold

_RULE_TABLE = {}
for _p in PASSES:
    _RULE_TABLE.update(_p.RULES)
_RULE_TABLE.update({
    "AL001": "mastic-allow without a written justification",
    "AL002": "mastic-allow that suppresses nothing",
    "XX000": "file does not parse",
})


def default_files() -> list:
    files = [REPO / "bench.py"]
    for root in ("mastic_tpu", "tools"):
        files += sorted((REPO / root).rglob("*.py"))
    return [f for f in files if f.exists()]


def _pass_applies(mod, rel: str, tree) -> bool:
    if mod is pallasck:
        return mod.in_scope(rel, tree)
    return mod.in_scope(rel)


def load_paths(paths):
    """Parse every path exactly once: (FileInfos, parse Findings)."""
    infos = []
    parse_findings = []
    for path in paths:
        info = load_file(pathlib.Path(path))
        if isinstance(info, Finding):
            parse_findings.append(info)
        else:
            infos.append(info)
    return (infos, parse_findings)


def analyze_paths(paths, only_passes=None, force_scope=False,
                  cache=None):
    """Run the passes over `paths`.

    only_passes: iterable of pass names (e.g. {"tracesafe"}) to run a
    subset; force_scope: apply the passes regardless of each pass's
    path scope (how the fixture self-tests drive files that live under
    tests/fixtures/); cache: an AnalysisCache to skip the per-file
    passes on content-identical files (None runs everything cold).
    Returns (findings, suppressed) where both are lists of Finding —
    `findings` is what gates CI, `suppressed` is what inline allows
    silenced.

    Each file is parsed once; the per-file passes and the
    whole-program layer (call graph + concurrency + interprocedural
    secret flow) share the same `FileInfo`s.
    """
    selected = [p for p in PASSES
                if only_passes is None or p.PASS_NAME in only_passes]
    pass_names = [p.PASS_NAME for p in selected]
    (infos, findings) = load_paths(paths)
    findings = list(findings)
    suppressed: list = []

    raw_by_rel = {info.rel: [] for info in infos}
    for info in infos:
        key = (cache.key(info, pass_names, force_scope)
               if cache is not None else None)
        rows = cache.get(key) if cache is not None else None
        if rows is not None:
            cache.hits += 1
            raw_by_rel[info.rel] = [
                Finding(rule, info.rel, line, msg)
                for (rule, line, msg) in rows]
            continue
        for mod in selected:
            if force_scope or _pass_applies(mod, info.rel, info.tree):
                raw_by_rel[info.rel] += mod.check(info)
        if cache is not None:
            cache.misses += 1
            cache.put(key, [[f.rule, f.line, f.msg]
                            for f in raw_by_rel[info.rel]])
    # The whole-program layer: one Program over the run's files —
    # cached as a unit (any changed file invalidates it), so a fully
    # warm run skips the call-graph build and every fixpoint.
    wp = [mod for mod in selected
          if getattr(mod, "WHOLE_PROGRAM", False)]
    if wp and infos:
        pkey = (cache.program_key(infos, pass_names, force_scope)
                if cache is not None else None)
        rows = cache.get(pkey) if cache is not None else None
        if rows is not None:
            cache.program_hit = True
            for (rule, rel, line, msg) in rows:
                if rel in raw_by_rel:
                    raw_by_rel[rel].append(Finding(rule, rel, line,
                                                   msg))
        else:
            program = callgraph.Program(infos)
            rows = []
            for mod in wp:
                for f in mod.check_program(program,
                                           force_scope=force_scope):
                    if f.rel in raw_by_rel:
                        raw_by_rel[f.rel].append(f)
                        rows.append([f.rule, f.rel, f.line, f.msg])
            if cache is not None:
                cache.put(pkey, rows)

    for info in infos:
        for f in raw_by_rel[info.rel]:
            sup = info.suppression_for(f)
            if sup is None:
                findings.append(f)
            else:
                sup.used = True
                f.sup_reason = sup.reason
                suppressed.append(f)
        # Suppression hygiene: every allow must carry a reason and
        # actually silence something.
        for sup in info.suppressions:
            if not sup.reason:
                findings.append(Finding(
                    "AL001", info.rel, sup.line,
                    "mastic-allow without a written justification "
                    "(add '— why this is fine')"))
            elif not sup.used and (only_passes is None
                                   or _covered(sup, selected)):
                findings.append(Finding(
                    "AL002", info.rel, sup.line,
                    f"mastic-allow for {', '.join(sup.ids)} suppresses "
                    "nothing — stale; remove it"))
    findings.sort(key=Finding.key)
    suppressed.sort(key=Finding.key)
    return (findings, suppressed)


def _covered(sup, selected) -> bool:
    """Only report a stale allow when the selected passes could have
    produced its rules (partial runs must not flag other passes')."""
    owned = set()
    for mod in selected:
        owned |= set(mod.RULES)
    return any(rid in owned for rid in sup.ids)


# -- suppression budget (ISSUE 8 satellite) ---------------------------

def suppression_stats(suppressed) -> dict:
    per_rule: dict = {}
    for f in suppressed:
        per_rule[f.rule] = per_rule.get(f.rule, 0) + 1
    return {"total": len(suppressed),
            "per_rule": dict(sorted(per_rule.items()))}


def load_budget() -> dict:
    return json.loads(BUDGET_FILE.read_text())


def check_budget(stats: dict, budget: dict) -> list:
    """Budget violations (strings); empty when within budget.  The
    gate is on the TOTAL: accepted risk may move between rules
    without a diff to the baseline, but may only GROW via an explicit
    baseline bump."""
    out = []
    if stats["total"] > budget["total"]:
        out.append(
            f"suppression budget exceeded: {stats['total']} "
            f"mastic-allow'd findings vs committed baseline "
            f"{budget['total']} (tools/analysis/allow_budget.json) — "
            f"fix the new findings or bump the baseline in this "
            f"diff with a justification")
    return out


def _render_stats(stats: dict, budget: dict) -> str:
    lines = ["suppressions per rule (committed baseline "
             f"{budget['total']} total):"]
    base_rules = budget.get("per_rule", {})
    for (rule, n) in stats["per_rule"].items():
        base = base_rules.get(rule, 0)
        delta = n - base
        mark = "" if delta == 0 else f"  ({delta:+d} vs baseline)"
        lines.append(f"  {rule}: {n}{mark}")
    lines.append(f"  total: {stats['total']} / {budget['total']}")
    return "\n".join(lines)


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="tools.analysis",
        description="trace-safety / dtype / secret-flow / pallas / "
                    "robustness / observability / concurrency "
                    "static analyzer (rules in USAGE.md)")
    parser.add_argument("paths", nargs="*",
                        help="files to analyze (default: mastic_tpu/, "
                             "tools/, bench.py)")
    parser.add_argument("--json", action="store_true",
                        help="emit findings as one JSON object")
    parser.add_argument("--pass", dest="only", action="append",
                        choices=[p.PASS_NAME for p in PASSES],
                        help="run only this pass (repeatable)")
    parser.add_argument("--force-scope", action="store_true",
                        help="apply passes regardless of path scope "
                             "(fixture testing)")
    parser.add_argument("--sarif", metavar="PATH",
                        help="write the run (findings + suppressions "
                             "with justifications) as SARIF 2.1.0")
    parser.add_argument("--stats", action="store_true",
                        help="print per-rule mastic-allow counts and "
                             "fail when the total exceeds the "
                             "committed allow_budget.json baseline")
    parser.add_argument("--no-cache", action="store_true",
                        help="ignore artifacts/analysis-cache/ and "
                             "run every per-file pass cold")
    args = parser.parse_args(argv)

    files = ([pathlib.Path(p).resolve() for p in args.paths]
             if args.paths else default_files())
    cache = None if args.no_cache else AnalysisCache()
    t0 = time.monotonic()
    (findings, suppressed_list) = analyze_paths(
        files, only_passes=set(args.only) if args.only else None,
        force_scope=args.force_scope, cache=cache)
    elapsed = time.monotonic() - t0

    stats = suppression_stats(suppressed_list)
    budget_problems: list = []
    if args.stats:
        budget_problems = check_budget(stats, load_budget())

    if args.sarif:
        reasons = {(f.rel, f.line, f.rule): (f.sup_reason or "")
                   for f in suppressed_list}
        log = to_sarif(_RULE_TABLE, findings, suppressed_list,
                       reasons)
        out_path = pathlib.Path(args.sarif)
        out_path.parent.mkdir(parents=True, exist_ok=True)
        out_path.write_text(json.dumps(log, indent=2) + "\n")

    if args.json:
        payload = {
            "findings": [f.as_json() for f in findings],
            "suppressed": [f.as_json() for f in suppressed_list],
            "files": len(files),
        }
        if args.stats:
            payload["stats"] = stats
            payload["budget_problems"] = budget_problems
            payload["cache"] = (
                {"hits": cache.hits, "misses": cache.misses,
                 "program_hit": cache.program_hit}
                if cache is not None else None)
            payload["wall_s"] = round(elapsed, 3)
        print(json.dumps(payload, indent=2))
    else:
        for f in findings:
            print(f.text())
        if args.stats:
            print(_render_stats(stats, load_budget()))
            if cache is not None:
                wp_state = ("warm" if cache.program_hit else "cold")
                print(f"  cache: {cache.hits} warm / "
                      f"{cache.hits + cache.misses} files, "
                      f"program layer {wp_state}")
            print(f"  wall: {elapsed:.2f}s")
            for problem in budget_problems:
                print(f"analyze: {problem}")
        print(f"analyze: {len(files)} files, {len(findings)} "
              f"finding(s), {len(suppressed_list)} suppressed")
    return 1 if (findings or budget_problems) else 0
