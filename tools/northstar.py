"""North-star-scale heavy hitters: stream a large report batch through
the chunked incremental runner end to end.

This is the flagship workload (reference driver semantics,
/root/reference/poc/examples.py:37-91, scaled up): device-batched
client sharding -> HostReportStore -> chunked incremental rounds with
per-chunk metrics and memory accounting.  Run it on the chip for the
real number, or on CPU (JAX_PLATFORMS=cpu) as the memory-accounted
simulation — the execution model and the compiled programs are
identical either way; only the rate changes.

Prints one JSON line:
  {"reports": N, "bits": B, "chunk_size": C, "levels": B,
   "wall_seconds": ..., "node_evals_total": ...,
   "node_evals_per_sec": ..., "per_chunk_evals_per_sec_p50": ...,
   "memory": {...}, "heavy_hitters": [...so many...], "ok": true}

Example (the VERDICT r3 target shape):
  JAX_PLATFORMS=cpu python tools/northstar.py --reports 100000 --bits 64
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--reports", type=int, default=100_000)
    parser.add_argument("--bits", type=int, default=64)
    parser.add_argument("--chunk-size", type=int, default=4096)
    parser.add_argument("--planted", type=int, default=3,
                        help="number of heavy-hitter values planted")
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args()

    t_start = time.time()

    def stamp(msg: str) -> None:
        print(f"[northstar {time.time() - t_start:8.1f}s] {msg}",
              file=sys.stderr, flush=True)

    import numpy as np
    import jax
    import jax.numpy as jnp

    requested = os.environ.get("JAX_PLATFORMS", "").strip()
    if requested and "axon" not in requested.split(","):
        jax.config.update("jax_platforms", requested)
    jax.config.update("jax_compilation_cache_dir",
                      "/tmp/mastic_tpu_jax_cache")
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

    from mastic_tpu import MasticCount
    from mastic_tpu.backend.mastic_jax import BatchedMastic
    from mastic_tpu.common import gen_rand
    from mastic_tpu.drivers.chunked import HostReportStore
    from mastic_tpu.drivers.heavy_hitters import HeavyHittersRun

    (R, bits, C) = (args.reports, args.bits, args.chunk_size)
    m = MasticCount(bits)
    bm = BatchedMastic(m)
    rng = np.random.default_rng(args.seed)
    stamp(f"device={jax.devices()[0].platform} reports={R} bits={bits} "
          f"chunk={C}")

    # Plant a few heavy values; the rest is a uniform tail that the
    # threshold prunes at level ~log2(R/threshold).
    planted = rng.integers(0, 1 << min(bits, 62), args.planted,
                           dtype=np.int64)
    share_heavy = 0.6
    alphas = np.zeros((R, bits), bool)
    heavy_rows = int(R * share_heavy)
    choice = rng.integers(0, args.planted, heavy_rows)
    vals = np.concatenate([
        planted[choice],
        rng.integers(0, 1 << min(bits, 62), R - heavy_rows,
                     dtype=np.int64)])
    for b in range(min(bits, 62)):
        alphas[:, b] = (vals >> (min(bits, 62) - 1 - b)) & 1
    threshold = int(R * share_heavy / args.planted * 0.5)

    # Device-batched client sharding, chunk by chunk, directly into
    # the host store (the client fleet axis; scalar clients would take
    # ~R seconds at 256 bits).
    stamp("shard: compiling client program")
    betas_one = np.stack([bm.spec.int_to_limbs(1)] * 2)
    shard_fn = jax.jit(
        lambda a, b, n, r: bm.shard_device(b"northstar", a, b, n, r))
    num_chunks = -(-R // C)
    arrays = None
    shard_t0 = time.time()
    for i in range(num_chunks):
        (lo, hi) = (i * C, min((i + 1) * C, R))
        idx = np.arange(lo, hi)
        if hi - lo < C:  # pad the tail chunk (same compiled program)
            idx = np.concatenate([idx, np.full(C - (hi - lo), lo)])
        a = jnp.asarray(alphas[idx])
        b = jnp.asarray(np.broadcast_to(betas_one, (C,) + betas_one.shape))
        n = jnp.asarray(rng.integers(0, 256, (C, 16), dtype=np.uint8))
        r = jnp.asarray(rng.integers(0, 256, (C, m.RAND_SIZE),
                                     dtype=np.uint8))
        (batch, ok) = shard_fn(a, b, n, r)
        assert bool(np.all(np.asarray(ok))), \
            "XOF rejection fired during synthetic shard (p ~ 2^-32)"
        chunk_store = HostReportStore.from_batch(batch, C)
        if arrays is None:
            arrays = {
                k: (np.zeros((R,) + v.shape[1:], v.dtype)
                    if isinstance(v, np.ndarray) else
                    tuple(np.zeros((R,) + p.shape[1:], p.dtype)
                          if isinstance(p, np.ndarray) else None
                          for p in v) if isinstance(v, tuple) else None)
                for (k, v) in chunk_store.arrays.items()}
        for (k, v) in chunk_store.arrays.items():
            if isinstance(v, np.ndarray):
                arrays[k][lo:hi] = v[:hi - lo]
            elif isinstance(v, tuple):
                for (dst, src) in zip(arrays[k], v):
                    if isinstance(src, np.ndarray):
                        dst[lo:hi] = src[:hi - lo]
        if i == 0:
            stamp(f"shard: chunk 0 done ({time.time() - shard_t0:.1f}s "
                  "incl compile)")
    shard_wall = time.time() - shard_t0
    stamp(f"shard: {R} reports in {shard_wall:.1f}s "
          f"({R / shard_wall:.0f} reports/s)")

    store = HostReportStore(arrays, R, C)
    vk = gen_rand(m.VERIFY_KEY_SIZE)
    run = HeavyHittersRun(m, b"northstar", {"default": threshold},
                          None, verify_key=vk, store=store)

    stamp(f"rounds: threshold={threshold} planted={args.planted}")
    agg_t0 = time.time()
    evals_total = 0
    chunk_rates: list = []
    level = 0
    while run.step():
        mx = run.metrics[-1]
        evals_total += mx.node_evals
        rates = [c["node_evals_per_sec"] for c in mx.extra["chunks"]]
        chunk_rates += rates
        if level % 8 == 0 or level == bits - 1:
            stamp(f"level {mx.level}: frontier={mx.frontier_width} "
                  f"accepted={mx.accepted}/{mx.reports_total} "
                  f"chunk_evals/s p50={sorted(rates)[len(rates)//2]:.0f}")
        level += 1
    agg_wall = time.time() - agg_t0

    hitters = run.result()
    expected = {
        tuple(bool((int(v) >> (min(bits, 62) - 1 - b)) & 1)
              if b < min(bits, 62) else False for b in range(bits))
        for v in planted}
    got = set(hitters)
    mem = run.runner.memory_accounting()
    p50 = sorted(chunk_rates)[len(chunk_rates) // 2]
    out = {
        "reports": R, "bits": bits, "chunk_size": C,
        "levels": len(run.metrics),
        "shard_seconds": round(shard_wall, 1),
        "wall_seconds": round(agg_wall, 1),
        "node_evals_total": evals_total,
        "node_evals_per_sec": round(evals_total / agg_wall, 1),
        "per_chunk_evals_per_sec_p50": round(p50, 1),
        "memory": mem,
        "heavy_hitters_found": len(hitters),
        "heavy_hitters_expected": len(expected),
        "ok": got == expected,
    }
    print(json.dumps(out), flush=True)
    if not out["ok"]:
        sys.exit(1)


if __name__ == "__main__":
    main()
