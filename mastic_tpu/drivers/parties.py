"""Process-separated aggregators: leader and helper as OS processes
exchanging the real wire encodings over sockets.

The reference PoC simulates all parties in one process
(/root/reference/poc/examples.py:51-59); its wire *formats* are fully
specified, though, and this module runs them over an actual transport:

    collector ──spawn──> leader (agg 0)     helper (agg 1)
        │ upload: nonce‖public share‖input share   (per party view)
        │ round:  encoded agg param
        │                  ▲
        │   helper ──prep share blob──> leader
        │   leader ──accept bitmap + prep msgs──> helper
        │ agg share bytes ──> collector (leader adds the bitmap)

Each party drives the *batched* backend for prep (one device program
over its whole report batch) and the scalar layer for the per-report
cross-party logic (prep_shares_to_prep / joint-rand confirmation),
exactly the split a real deployment would have.  Lanes where XOF
rejection sampling fires are recomputed through the party's own
scalar path before the exchange, so the fallback never crosses a
trust boundary.

The DAP-style topology: the helper only talks to the leader for prep;
the collector only sees aggregate shares (plus the leader's accept
count) — reference README's deployment sketch and SURVEY.md §2.3's
communication-backend plan.
"""

import json
import socket
import subprocess
import sys
from typing import Optional

import numpy as np

from .. import mastic as mastic_mod
from ..mastic import Mastic
from .. import wire


def instantiate(spec: dict) -> Mastic:
    """{"class": "MasticCount", "args": [2]} -> instance."""
    cls = getattr(mastic_mod, spec["class"])
    return cls(*spec["args"])


def _channel(sock: socket.socket):
    return sock.makefile("rwb")


class AggregatorParty:
    """One aggregator's protocol engine (transport-agnostic)."""

    def __init__(self, mastic: Mastic, agg_id: int, verify_key: bytes,
                 ctx: bytes):
        from ..backend.mastic_jax import BatchedMastic

        self.m = mastic
        self.agg_id = agg_id
        self.verify_key = verify_key
        self.ctx = ctx
        self.bm = BatchedMastic(mastic)
        self.reports: list = []
        self.arrays: Optional[dict] = None
        self._prep = None
        self._resolve_fns: dict = {}

    # -- upload channel --------------------------------------------

    def load_reports(self, blobs: list[bytes]) -> None:
        self.reports = [wire.decode_report(self.m, self.agg_id, blob)
                        for blob in blobs]
        self.arrays = self.bm.marshal_party_reports(self.agg_id,
                                                    self.reports)

    # -- prep ------------------------------------------------------

    def prep_blob(self, agg_param) -> bytes:
        """Run the batched prep and encode this party's prep shares:
        R fixed-size rows (eval proof ‖ [jr part] ‖ [verifier])."""
        import jax

        assert self.arrays is not None
        a = self.arrays
        bm = self.bm
        fn = jax.jit(lambda n, c, k, p, s, j: bm.prep(
            self.agg_id, self.verify_key, self.ctx, agg_param,
            n, c, k, proof_shares=p, seeds=s, peer_jr_parts=j))
        p = fn(a["nonces"], a["cws"], a["keys"], a["proof_shares"],
               a["seeds"], a["peer_jr_parts"])
        self._prep = self._scalar_fallback(agg_param, p)
        return self._encode_prep(agg_param, self._prep)

    def _scalar_fallback(self, agg_param, p):
        """Recompute lanes where XOF rejection sampling fired through
        this party's scalar layer (vdaf-13 §6.2 rejection loop) and
        splice the exact rows in."""
        ok = np.asarray(p.ok)
        if ok.all():
            return p
        spec = self.bm.spec
        out_share = np.asarray(p.out_share).copy()
        eval_proof = np.asarray(p.eval_proof).copy()
        verifier = (None if p.verifier is None
                    else np.asarray(p.verifier).copy())
        jr_part = (None if p.joint_rand_part is None
                   else np.asarray(p.joint_rand_part).copy())
        jr_seed = (None if p.joint_rand_seed is None
                   else np.asarray(p.joint_rand_seed).copy())
        for r in np.flatnonzero(~ok):
            (nonce, public_share, input_share) = self.reports[r]
            (state, share) = self.m.prep_init(
                self.verify_key, self.ctx, self.agg_id, agg_param,
                nonce, public_share, input_share)
            (out, seed) = state
            (proof, ver, part) = share
            out_share[r] = [spec.int_to_limbs(x.int()) for x in out]
            eval_proof[r] = np.frombuffer(proof, np.uint8)
            if verifier is not None and ver is not None:
                verifier[r] = [spec.int_to_limbs(x.int()) for x in ver]
            if jr_part is not None and part is not None:
                jr_part[r] = np.frombuffer(part, np.uint8)
            if jr_seed is not None and seed is not None:
                jr_seed[r] = np.frombuffer(seed, np.uint8)
        return p._replace(
            out_share=out_share, eval_proof=eval_proof,
            verifier=verifier, joint_rand_part=jr_part,
            joint_rand_seed=jr_seed)

    def _encode_prep(self, agg_param, p) -> bytes:
        (_level, _prefixes, do_weight_check) = agg_param
        num = np.asarray(p.eval_proof).shape[0]
        parts = [np.asarray(p.eval_proof)]
        if do_weight_check:
            if self.m.flp.JOINT_RAND_LEN > 0:
                parts.append(np.asarray(p.joint_rand_part))
            ver = np.asarray(self.bm.spec.plain_to_le_bytes(
                p.verifier)).reshape(num, -1)
            parts.append(ver)
        return np.concatenate(parts, axis=-1).tobytes()

    # -- leader: the prep-share exchange ---------------------------

    def resolve(self, agg_param, peer_blob: bytes) -> tuple:
        """Leader side of prep_shares_to_prep over the report batch:
        returns (accept bitmap bytes, prep-msg blob).

        Vectorized over the report axis (scalar semantics:
        mastic.py prep_shares_to_prep + the leader's own joint-rand
        confirmation): eval-proof equality, the FLP decide over the
        summed verifier shares (the batched decide kernel), and the
        joint-rand seed derivation all run as single batched ops.  A
        verifier element outside the field (possible only from a
        misbehaving helper) rejects that report instead of aborting
        the batch."""
        import jax.numpy as jnp

        (_level, _prefixes, do_wc) = agg_param
        size = wire.prep_share_size(self.m, agg_param)
        num = len(self.reports)
        p = self._prep
        if len(peer_blob) != num * size:
            # A protocol-level refusal, not a numpy reshape traceback:
            # a truncated or oversized exchange from a misbehaving
            # peer aborts the round loudly and attributably.
            raise ValueError(
                f"malformed prep-share exchange from peer: got "
                f"{len(peer_blob)} bytes, expected {num} x {size}")
        peer = np.frombuffer(peer_blob, np.uint8).reshape(num, size)
        use_jr = (self.m.flp.JOINT_RAND_LEN > 0 and do_wc)
        fn = self._resolve_fn(do_wc, use_jr, num, size)
        if do_wc:
            (accept, prep_msgs) = fn(
                jnp.asarray(peer), p.eval_proof, p.verifier,
                p.joint_rand_part, p.joint_rand_seed)
        else:
            (accept, prep_msgs) = fn(jnp.asarray(peer), p.eval_proof)
        accept = np.asarray(accept)
        prep_msgs = (np.asarray(prep_msgs) if prep_msgs is not None
                     else None)

        bitmap = np.packbits(accept, bitorder="little").tobytes()
        blob = b"".join(
            wire.frame(prep_msgs[r].tobytes()
                       if accept[r] and prep_msgs is not None else b"")
            for r in range(num))
        return (accept, bitmap + blob)

    def _resolve_fn(self, do_wc: bool, use_jr: bool, num: int,
                    size: int):
        """One jitted program for the whole batched exchange (eager
        dispatch of the Keccak/NTT kernels at 10k reports costs more
        than the math).  Cached per round *kind* only — jax.jit
        already specializes per (num, size) shape."""
        import jax
        import jax.numpy as jnp

        del num, size  # shape specialization is jit's job
        key = (do_wc, use_jr)
        fn = self._resolve_fns.get(key)
        if fn is not None:
            return fn
        (bm, ctx, elem) = (self.bm, self.ctx, self.m.field.ENCODED_SIZE)

        if not do_wc:
            def fn(peer, eval_proof):
                return (jnp.all(eval_proof == peer[:, :32], axis=-1),
                        None)
        else:
            def fn(peer, eval_proof, verifier_own, jr_part_own,
                   jr_seed_own):
                accept = jnp.all(eval_proof == peer[:, :32], axis=-1)
                off = 32
                if use_jr:
                    part1 = peer[:, off:off + 32]
                    off += 32
                ver_bytes = peer[:, off:]
                vlen = ver_bytes.shape[1] // elem
                (ver1, in_range) = bm.spec.limbs_from_le_bytes(
                    ver_bytes.reshape(ver_bytes.shape[0], vlen, elem))
                verifier = bm.spec.add(verifier_own, ver1)
                accept &= bm.bflp.decide(verifier)
                accept &= jnp.all(in_range, axis=-1)
                prep_msgs = None
                if use_jr:
                    # prep msg = joint-rand seed from [leader, helper]
                    # parts; the leader's confirmation compares it to
                    # its own predicted seed (prep_next semantics —
                    # the helper runs the same check in confirm()).
                    prep_msgs = bm.joint_rand_seed(ctx, jr_part_own,
                                                   part1)
                    accept &= jnp.all(prep_msgs == jr_seed_own,
                                      axis=-1)
                return (accept, prep_msgs)

        fn = jax.jit(fn)
        self._resolve_fns[key] = fn
        return fn

    def confirm(self, agg_param, resolution: bytes) -> np.ndarray:
        """Helper side: parse the leader's bitmap + prep msgs, run the
        joint-rand confirmation (prep_next semantics) per report."""
        num = len(self.reports)
        nbytes = (num + 7) // 8
        if len(resolution) < nbytes:
            # Same protocol-level refusal as the leader's resolve():
            # a truncating peer aborts loudly, not via numpy/struct
            # tracebacks mid-parse.
            raise ValueError(
                f"malformed resolution from leader: got "
                f"{len(resolution)} bytes, accept bitmap alone needs "
                f"{nbytes}")
        accept = np.unpackbits(
            np.frombuffer(resolution[:nbytes], np.uint8),
            bitorder="little")[:num].astype(bool)
        rest = resolution[nbytes:]
        use_jr = (self.m.flp.JOINT_RAND_LEN > 0 and agg_param[2])
        jr_seed = (None if self._prep.joint_rand_seed is None
                   else np.asarray(self._prep.joint_rand_seed))
        for r in range(num):
            try:
                (msg, rest) = wire.unframe(rest)
            except Exception as exc:
                raise ValueError(
                    f"malformed resolution from leader: prep msg "
                    f"{r} of {num} truncated") from exc
            if not accept[r]:
                continue
            if use_jr:
                assert jr_seed is not None
                if msg != jr_seed[r].tobytes():
                    accept[r] = False  # joint-rand confirmation failed
            elif msg != b"":
                accept[r] = False
        if rest:
            # Strict length symmetry with resolve(): trailing bytes
            # are a malformed exchange, not ignorable padding.
            raise ValueError(
                f"malformed resolution from leader: {len(rest)} "
                f"trailing bytes after the last prep msg")
        return accept

    # -- aggregation -----------------------------------------------

    def agg_share(self, agg_param, accept: np.ndarray) -> bytes:
        import jax.numpy as jnp

        agg = self.bm.aggregate(jnp.asarray(self._prep.out_share),
                                jnp.asarray(accept))
        return np.asarray(
            self.bm.spec.plain_to_le_bytes(agg)).tobytes()


# -- the party process main loop -------------------------------------

def party_main(argv: list[str]) -> None:
    # The ambient sitecustomize force-overrides jax's platform config
    # to the remote TPU backend; make the caller's JAX_PLATFORMS
    # authoritative again (the test fabric runs parties on CPU, and a
    # down TPU tunnel must not be able to hang a CPU party).
    import os

    import jax

    requested = os.environ.get("JAX_PLATFORMS", "").strip()
    if requested and "axon" not in requested.split(","):
        jax.config.update("jax_platforms", requested)
    # Share the persistent compile cache with the parent fabric.
    jax.config.update("jax_compilation_cache_dir",
                      os.environ.get("JAX_COMPILATION_CACHE_DIR",
                                     "/tmp/mastic_tpu_jax_cache"))
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

    debug = os.environ.get("MASTIC_PARTY_DEBUG") == "1"

    cfg = json.loads(argv[0])
    agg_id = cfg["agg_id"]

    def trace(what: str) -> None:
        if debug:
            print(f"[party {agg_id}] {what}", file=sys.stderr,
                  flush=True)

    mastic = instantiate(cfg["mastic"])
    party = AggregatorParty(mastic, agg_id,
                            bytes.fromhex(cfg["verify_key"]),
                            bytes.fromhex(cfg["ctx"]))
    trace("engine up, connecting")

    coll_sock = socket.create_connection(("127.0.0.1",
                                          cfg["collector_port"]))
    coll = _channel(coll_sock)
    wire.send_msg(coll, bytes([agg_id]))

    peer = None
    if agg_id == 0:
        lst = socket.create_server(("127.0.0.1", 0))
        wire.send_msg(coll, lst.getsockname()[1].to_bytes(2, "little"))
        trace("listening for helper")
        (peer_sock, _) = lst.accept()
        peer = _channel(peer_sock)
    else:
        port_msg = wire.recv_msg(coll)
        assert port_msg is not None
        peer_sock = socket.create_connection(
            ("127.0.0.1", int.from_bytes(port_msg, "little")))
        peer = _channel(peer_sock)
    trace("peer channel up")

    while True:
        msg = wire.recv_msg(coll)
        if msg is None or msg[:1] == b"\x03":
            trace("shutdown")
            break
        if msg[:1] == b"\x01":  # upload
            body = msg[1:]
            (num,) = np.frombuffer(body[:4], np.uint32)
            rest = body[4:]
            blobs = []
            for _ in range(int(num)):
                (blob, rest) = wire.unframe(rest)
                blobs.append(blob)
            party.load_reports(blobs)
            trace(f"loaded {num} reports")
            wire.send_msg(coll, b"ok")
        elif msg[:1] == b"\x02":  # one aggregation round
            agg_param = mastic.decode_agg_param(msg[1:])
            trace(f"round level={agg_param[0]} compiling prep")
            blob = party.prep_blob(agg_param)
            trace("prep done, exchanging")
            if agg_id == 1:
                wire.send_msg(peer, blob)
                resolution = wire.recv_msg(peer)
                assert resolution is not None
                accept = party.confirm(agg_param, resolution)
                wire.send_msg(coll, party.agg_share(agg_param, accept))
            else:
                peer_blob = wire.recv_msg(peer)
                assert peer_blob is not None
                (accept, resolution) = party.resolve(agg_param,
                                                     peer_blob)
                wire.send_msg(peer, resolution)
                bitmap = np.packbits(accept,
                                     bitorder="little").tobytes()
                wire.send_msg(coll, bitmap
                              + party.agg_share(agg_param, accept))
            trace("round done")


# -- collector side --------------------------------------------------

class ProcessCollector:
    """Spawns the two aggregator processes and drives rounds against
    them; the in-process analog is drivers/heavy_hitters.run_round."""

    def __init__(self, mastic: Mastic, mastic_spec: dict, ctx: bytes,
                 verify_key: bytes):
        self.m = mastic
        self.server = socket.create_server(("127.0.0.1", 0))
        port = self.server.getsockname()[1]
        env_cfg = {"mastic": mastic_spec, "ctx": ctx.hex(),
                   "verify_key": verify_key.hex(),
                   "collector_port": port}
        self.procs = [
            subprocess.Popen(
                [sys.executable, "-m", "mastic_tpu.drivers.parties",
                 json.dumps({**env_cfg, "agg_id": agg_id})],
                cwd=_repo_root(), stdout=sys.stderr, stderr=sys.stderr)
            for agg_id in range(2)
        ]
        chans = {}
        for _ in range(2):
            (sock, _addr) = self.server.accept()
            chan = _channel(sock)
            hello = wire.recv_msg(chan)
            assert hello is not None
            chans[hello[0]] = chan
        (self.leader, self.helper) = (chans[0], chans[1])
        leader_port = wire.recv_msg(self.leader)
        assert leader_port is not None
        wire.send_msg(self.helper, leader_port)

    def upload(self, reports: list) -> None:
        """reports: [(nonce, public_share, input_shares)] with BOTH
        input shares (the collector here doubles as the upload relay —
        clients talk to aggregators directly in a real deployment)."""
        self.num_reports = len(reports)
        for (agg_id, chan) in ((0, self.leader), (1, self.helper)):
            blobs = [
                wire.encode_report(self.m, agg_id, nonce, ps,
                                   shares[agg_id])
                for (nonce, ps, shares) in reports
            ]
            body = np.uint32(len(blobs)).tobytes() \
                + b"".join(wire.frame(b) for b in blobs)
            wire.send_msg(chan, b"\x01" + body)
        for chan in (self.leader, self.helper):
            assert wire.recv_msg(chan) == b"ok"

    def round(self, agg_param) -> tuple:
        """Run one aggregation round; returns (agg_result, accept)."""
        encoded = b"\x02" + self.m.encode_agg_param(agg_param)
        wire.send_msg(self.leader, encoded)
        wire.send_msg(self.helper, encoded)
        leader_msg = wire.recv_msg(self.leader)
        helper_msg = wire.recv_msg(self.helper)
        assert leader_msg is not None and helper_msg is not None
        # leader payload: accept bitmap + agg share
        share_size = wire.agg_share_size(self.m, agg_param)
        nbytes = len(leader_msg) - share_size
        if nbytes != (self.num_reports + 7) // 8 \
                or len(helper_msg) != share_size:
            raise ValueError(
                f"malformed round payload: leader sent "
                f"{len(leader_msg)} bytes (want bitmap "
                f"{(self.num_reports + 7) // 8} + share {share_size}), "
                f"helper sent {len(helper_msg)} (want {share_size})")
        accept = np.unpackbits(
            np.frombuffer(leader_msg[:nbytes], np.uint8),
            bitorder="little")[:self.num_reports].astype(bool)
        agg0 = wire.decode_agg_share(self.m, agg_param,
                                     leader_msg[nbytes:])
        agg1 = wire.decode_agg_share(self.m, agg_param, helper_msg)
        num = int(accept.sum())
        result = self.m.unshard(agg_param, [agg0, agg1], num)
        return (result, accept, (leader_msg[nbytes:], helper_msg))

    def close(self) -> None:
        for chan in (self.leader, self.helper):
            try:
                wire.send_msg(chan, b"\x03")
            except (BrokenPipeError, OSError):
                pass
        for proc in self.procs:
            proc.wait(timeout=60)
        self.server.close()


def _repo_root() -> str:
    import pathlib
    return str(pathlib.Path(__file__).resolve().parents[2])


if __name__ == "__main__":
    party_main(sys.argv[1:])
