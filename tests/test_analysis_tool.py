"""Fixture self-tests for every tools/analysis rule (fast tier).

Each analyzer rule has at least one known-bad fixture it must flag and
one known-good fixture it must pass (tests/fixtures/analysis/), plus
the suppression mechanics (justified allow silences, bare allow and
stale allow are findings) and the shipped-tree gate (`make analyze`
must exit 0 on the repo as committed).
"""

import pathlib

import pytest

from tools import analysis

FIXTURES = pathlib.Path(__file__).parent / "fixtures" / "analysis"

# rule -> (bad fixture, good fixture, pass name)
CASES = {
    "TS001": ("ts001_bad.py", "ts001_good.py", "tracesafe"),
    "TS002": ("ts002_bad.py", "ts002_good.py", "tracesafe"),
    "TS003": ("ts003_bad.py", "ts003_good.py", "tracesafe"),
    "TS004": ("ts004_bad.py", "ts004_good.py", "tracesafe"),
    "DT001": ("dt001_bad.py", "dt001_good.py", "dtypes"),
    "DT002": ("dt002_bad.py", "dt002_good.py", "dtypes"),
    "DT003": ("dt003_bad.py", "dt003_good.py", "dtypes"),
    "SF001": ("sf001_bad.py", "sf001_good.py", "secretflow"),
    "SF002": ("sf002_bad.py", "sf002_good.py", "secretflow"),
    "PL001": ("pl001_bad.py", "pl001_good.py", "pallasck"),
    "PL002": ("pl002_bad.py", "pl002_good.py", "pallasck"),
    "PL003": ("pl003_bad.py", "pl003_good.py", "pallasck"),
    "PL004": ("pl004_bad.py", "pl004_good.py", "pallasck"),
    "RB001": ("rb001_bad.py", "rb001_good.py", "robustness"),
    "RB002": ("rb002_bad.py", "rb002_good.py", "robustness"),
    "RB003": ("rb003_bad.py", "rb003_good.py", "robustness"),
    "RB004": ("rb004_bad.py", "rb004_good.py", "robustness"),
    "RB005": ("rb005_bad.py", "rb005_good.py", "robustness"),
    "RB006": ("rb006_bad.py", "rb006_good.py", "robustness"),
    "OB001": ("ob001_bad.py", "ob001_good.py", "observability"),
    "CC001": ("cc001_bad.py", "cc001_good.py", "concurrency"),
    "CC002": ("cc002_bad.py", "cc002_good.py", "concurrency"),
    "CC003": ("cc003_bad.py", "cc003_good.py", "concurrency"),
    "CC004": ("cc004_bad.py", "cc004_good.py", "concurrency"),
    "SF003": ("sf003_bad.py", "sf003_good.py", "secretflow"),
    "SF004": ("sf004_bad.py", "sf004_good.py", "secretflow"),
    "SF005": ("sf005_bad.py", "sf005_good.py", "secretflow"),
    "RL001": ("rl001_bad.py", "rl001_good.py", "lifetime"),
    "RL002": ("rl002_bad.py", "rl002_good.py", "lifetime"),
    "RL003": ("rl003_bad.py", "rl003_good.py", "lifetime"),
    "RL004": ("rl004_bad.py", "rl004_good.py", "lifetime"),
    "RL005": ("rl005_bad.py", "rl005_good.py", "lifetime"),
    "EV001": ("ev001_bad.py", "ev001_good.py", "evloop"),
    "EV002": ("ev002_bad.py", "ev002_good.py", "evloop"),
    "EV003": ("ev003_bad.py", "ev003_good.py", "evloop"),
}


def run_fixture(name: str, pass_name: str):
    return analysis.analyze_paths([FIXTURES / name],
                                  only_passes={pass_name},
                                  force_scope=True)


@pytest.mark.parametrize("rule", sorted(CASES))
def test_bad_fixture_is_flagged(rule):
    """Each bad fixture fires EXACTLY its own rule — a fixture that
    trips a second rule is testing an accident, not the rule."""
    (bad, _good, pass_name) = CASES[rule]
    (findings, _suppressed) = run_fixture(bad, pass_name)
    rules_hit = {f.rule for f in findings}
    assert rules_hit == {rule}, (
        f"{bad} must trigger {rule} and only {rule}; got "
        f"{[f.text() for f in findings]}")


@pytest.mark.parametrize("rule", sorted(CASES))
def test_good_fixture_is_clean(rule):
    (_bad, good, pass_name) = CASES[rule]
    (findings, suppressed) = run_fixture(good, pass_name)
    assert findings == [] and suppressed == [], (
        f"{good} must be clean; got {[f.text() for f in findings]}")


def test_every_rule_has_a_fixture_case():
    """Every rule ID in _RULE_TABLE (meta-rules aside) has a bad AND
    a good fixture on disk, and this table covers them all — a new
    rule cannot ship untested."""
    declared = set()
    for mod in analysis.PASSES:
        declared |= set(mod.RULES)
    assert declared == set(CASES), (
        "every analyzer rule needs a bad+good fixture pair here")
    meta = {"AL001", "AL002", "XX000"}
    assert set(analysis._RULE_TABLE) == declared | meta
    for (rule, (bad, good, _pass)) in CASES.items():
        assert (FIXTURES / bad).exists(), f"{rule}: missing {bad}"
        assert (FIXTURES / good).exists(), f"{rule}: missing {good}"


# -- suppression mechanics -------------------------------------------

def test_justified_suppression_silences_finding():
    (findings, suppressed) = run_fixture("al_good.py", "secretflow")
    assert findings == []
    assert [f.rule for f in suppressed] == ["SF001"]


def test_suppression_covers_multiline_statement():
    (findings, suppressed) = run_fixture("al_multiline_good.py",
                                         "secretflow")
    assert findings == []
    assert {f.rule for f in suppressed} == {"SF002"}
    assert len(suppressed) == 2    # both lines of the statement


def test_bare_suppression_is_flagged():
    (findings, _suppressed) = run_fixture("al001_bad.py", "secretflow")
    assert [f.rule for f in findings] == ["AL001"]


def test_stale_suppression_is_flagged():
    (findings, _suppressed) = run_fixture("al002_bad.py", "secretflow")
    assert [f.rule for f in findings] == ["AL002"]


def test_syntax_error_is_a_finding():
    (findings, _suppressed) = run_fixture("xx000_bad.py", "tracesafe")
    assert [f.rule for f in findings] == ["XX000"]


# -- the gate itself -------------------------------------------------

_TREE_RUN = None


def _tree_run():
    """The full-tree analysis, run once per test session (the
    whole-program layer makes it the suite's priciest call)."""
    global _TREE_RUN
    if _TREE_RUN is None:
        _TREE_RUN = analysis.analyze_paths(analysis.default_files())
    return _TREE_RUN


def test_shipped_tree_is_clean():
    """`make analyze` must exit 0 on the repo as committed: every real
    finding is fixed or carries a justified inline mastic-allow."""
    (findings, suppressed) = _tree_run()
    assert findings == [], [f.text() for f in findings]
    # The suppressed set is the documented-risk register; it must be
    # non-empty (the passes do fire on real code) and every entry
    # carries a justification (AL001 would have failed above).
    assert len(suppressed) >= 4
    classes = {f.rule[:2] for f in suppressed}
    assert {"TS", "DT", "SF", "PL", "CC"} <= classes, (
        "each pass class must have at least one documented real "
        f"finding; got {classes}")
    # ISSUE 8 acceptance: the whole-program secret-flow rules found
    # (and the tree documents) real service-plane flows, not just
    # the scalar-layer SF001/SF002 register.
    assert any(f.rule in ("SF003", "SF004", "SF005")
               for f in suppressed), (
        "the interprocedural secret-flow register is empty")


def test_ingest_worker_is_a_discovered_thread_root():
    """ISSUE 10 regression: the concurrency pass only proves the
    ingest front's lock discipline if the call-graph actually
    discovers `_IngestFront._worker` as a thread root and walks the
    admission path from it — a silently-undiscovered root would make
    the CC001-clean verdict vacuous."""
    import pathlib

    from tools.analysis import callgraph, load_paths

    repo = pathlib.Path(__file__).parent.parent
    files = sorted((repo / "mastic_tpu").rglob("*.py"))
    files.append(repo / "tools" / "serve.py")
    (infos, parse_findings) = load_paths(files)
    assert parse_findings == []
    program = callgraph.Program(infos)
    roots = [r.qual for roots in program.thread_roots.values()
             for r in roots]
    workers = [q for q in roots if "_IngestFront._worker" in q]
    assert workers, f"ingest worker not a thread root: {roots}"
    # The admission path is reachable from that root AND from the
    # main entry group — exactly the cross-thread shape CC001 audits.
    admit = next(fn for fn in program.functions.values()
                 if fn.qual.endswith("_Tenant.admit_decoded"))
    groups = program.root_groups(admit)
    assert any("_worker" in g for g in groups), groups
    assert len(groups) >= 2, (
        f"admit_decoded must span thread roots, got {groups}")


def test_suppression_budget_within_baseline():
    """The committed allow_budget.json covers the shipped tree, and
    the gate actually trips when the budget shrinks below reality."""
    (_findings, suppressed) = _tree_run()
    stats = analysis.suppression_stats(suppressed)
    budget = analysis.load_budget()
    assert analysis.check_budget(stats, budget) == []
    # One more allow than budgeted must fail the gate.
    tight = dict(budget)
    tight["total"] = stats["total"] - 1
    assert analysis.check_budget(stats, tight)


def test_stats_cli_enforces_budget():
    """The --stats flag renders the per-rule table and gates on the
    committed baseline (scoped to one allowed fixture so the CLI run
    stays cheap; the full-tree budget math is covered above)."""
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, "-m", "tools.analysis", "--stats",
         "--pass", "secretflow", "--force-scope",
         str(FIXTURES / "al_good.py")],
        capture_output=True, text=True,
        cwd=str(pathlib.Path(__file__).parent.parent))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "suppressions per rule" in proc.stdout
    assert "total: 1 /" in proc.stdout


# -- SARIF output ----------------------------------------------------

def _sarif_for(paths, **kw):
    (findings, suppressed) = analysis.analyze_paths(paths, **kw)
    reasons = {(f.rel, f.line, f.rule): (f.sup_reason or "")
               for f in suppressed}
    return analysis.to_sarif(analysis._RULE_TABLE, findings,
                             suppressed, reasons)


def test_sarif_structure_is_valid_2_1_0():
    """Structural validation against the SARIF 2.1.0 schema subset:
    required top-level keys, rule indexing, one physical location
    with a 1-based line per result, inSource suppressions."""
    log = _sarif_for([FIXTURES / "sf004_bad.py"],
                     only_passes={"secretflow"}, force_scope=True)
    assert log["version"] == "2.1.0"
    assert "sarif-schema-2.1.0" in log["$schema"]
    assert len(log["runs"]) == 1
    run = log["runs"][0]
    driver = run["tool"]["driver"]
    assert driver["name"]
    rule_ids = [r["id"] for r in driver["rules"]]
    assert rule_ids == sorted(set(rule_ids))
    assert set(rule_ids) == set(analysis._RULE_TABLE)
    for r in driver["rules"]:
        assert r["shortDescription"]["text"]
    assert run["results"], "the bad fixture must yield a result"
    for res in run["results"]:
        assert rule_ids[res["ruleIndex"]] == res["ruleId"]
        assert res["level"] == "error"
        assert res["message"]["text"]
        loc = res["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"].endswith(".py")
        assert loc["region"]["startLine"] >= 1


def test_sarif_carries_suppressions_with_justifications():
    log = _sarif_for([FIXTURES / "al_good.py"],
                     only_passes={"secretflow"}, force_scope=True)
    sups = [r for r in log["runs"][0]["results"]
            if "suppressions" in r]
    assert sups, "the allowed finding must appear, marked suppressed"
    for res in sups:
        assert res["suppressions"][0]["kind"] == "inSource"
        assert res["suppressions"][0]["justification"]


def test_sarif_cli_writes_file(tmp_path):
    import json
    import subprocess
    import sys

    out = tmp_path / "analysis.sarif"
    proc = subprocess.run(
        [sys.executable, "-m", "tools.analysis", "--sarif", str(out),
         "--pass", "secretflow", "--force-scope",
         str(FIXTURES / "sf001_bad.py")],
        capture_output=True, text=True,
        cwd=str(pathlib.Path(__file__).parent.parent))
    assert proc.returncode == 1
    log = json.loads(out.read_text())
    assert log["version"] == "2.1.0"
    assert log["runs"][0]["results"][0]["ruleId"] == "SF001"


# -- the whole-program layer ------------------------------------------

def test_interprocedural_taint_crosses_call_boundary():
    """sf004_bad routes the key through a helper's return value —
    only the call-graph propagation can see it."""
    (findings, _s) = run_fixture("sf004_bad.py", "secretflow")
    assert [f.rule for f in findings] == ["SF004"]


def test_thread_reachability_drives_cc001():
    """cc001_bad's unlocked write is a finding ONLY because _loop is
    a discovered thread root; the same file without the Thread is
    clean (no cross-thread state)."""
    import ast

    src = (FIXTURES / "cc001_bad.py").read_text()
    assert "threading.Thread" in src
    stripped = src.replace(
        "        self.thread = threading.Thread(target=self._loop)\n",
        "")
    ast.parse(stripped)   # still a valid module
    target = FIXTURES / "cc001_bad.py"
    tmp = target.parent / "_cc001_nothread_tmp.py"
    tmp.write_text(stripped)
    try:
        (findings, _s) = analysis.analyze_paths(
            [tmp], only_passes={"concurrency"}, force_scope=True)
        assert findings == [], [f.text() for f in findings]
    finally:
        tmp.unlink()


def test_cli_json_output():
    import json
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, "-m", "tools.analysis", "--json",
         str(FIXTURES / "sf001_bad.py"), "--pass", "secretflow",
         "--force-scope"],
        capture_output=True, text=True,
        cwd=str(pathlib.Path(__file__).parent.parent))
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)
    assert payload["findings"][0]["rule"] == "SF001"


# -- ISSUE 11: the network-front scope extension ----------------------

# Net-flavored fixture pairs: the same rules, modeled on the failure
# shapes an internet-facing upload door has (deadline-less handler
# reads, unbounded per-client tables, secret-bearing HTTP error
# bodies).  They ride NEXT TO the canonical pairs in CASES — every
# rule keeps exactly one canonical pair there; these prove the rules
# catch the network shapes too.
NET_CASES = {
    "RB001": ("rb001_net_bad.py", "rb001_net_good.py", "robustness"),
    "RB004": ("rb004_net_bad.py", "rb004_net_good.py", "robustness"),
    "SF004": ("sf004_net_bad.py", "sf004_net_good.py", "secretflow"),
}

# ISSUE 14: the transport-security scope extension.  TLS-flavored
# pairs: a deadline-less ssl handshake (RB001 — a silent dialer
# wedges the accept thread mid-handshake) and private-key bytes
# leaving the process (SF004 — credential egress; only file PATHS
# may cross).  Same ride-along convention as NET_CASES.
TLS_CASES = {
    "RB001": ("rb001_tls_bad.py", "rb001_tls_good.py", "robustness"),
    "SF004": ("sf004_key_bad.py", "sf004_key_good.py", "secretflow"),
}


@pytest.mark.parametrize("rule", sorted(TLS_CASES))
def test_tls_bad_fixture_is_flagged(rule):
    (bad, _good, pass_name) = TLS_CASES[rule]
    (findings, _suppressed) = run_fixture(bad, pass_name)
    rules_hit = {f.rule for f in findings}
    assert rules_hit == {rule}, (
        f"{bad} must trigger {rule} and only {rule}; got "
        f"{[f.text() for f in findings]}")


@pytest.mark.parametrize("rule", sorted(TLS_CASES))
def test_tls_good_fixture_is_clean(rule):
    (_bad, good, pass_name) = TLS_CASES[rule]
    (findings, suppressed) = run_fixture(good, pass_name)
    assert findings == [] and suppressed == [], (
        f"{good} must be clean; got {[f.text() for f in findings]}")


def test_transport_security_files_in_analyzer_scope():
    """tools/party.py and tools/certs.py (ISSUE 14) are inside both
    the robustness and whole-program secret-flow reporting scopes: a
    deadline-less handshake or a key egress in the credential/party
    tooling must be a finding, not a blind spot."""
    from tools.analysis import robustness, secretflow

    for rel in ("tools/party.py", "tools/certs.py"):
        assert robustness.in_scope(rel), rel
        assert secretflow.wp_in_scope(rel), rel



@pytest.mark.parametrize("rule", sorted(NET_CASES))
def test_net_bad_fixture_is_flagged(rule):
    (bad, _good, pass_name) = NET_CASES[rule]
    (findings, _suppressed) = run_fixture(bad, pass_name)
    rules_hit = {f.rule for f in findings}
    assert rules_hit == {rule}, (
        f"{bad} must trigger {rule} and only {rule}; got "
        f"{[f.text() for f in findings]}")


@pytest.mark.parametrize("rule", sorted(NET_CASES))
def test_net_good_fixture_is_clean(rule):
    (_bad, good, pass_name) = NET_CASES[rule]
    (findings, suppressed) = run_fixture(good, pass_name)
    assert findings == [] and suppressed == [], (
        f"{good} must be clean; got {[f.text() for f in findings]}")


def test_net_package_is_in_analyzer_scope():
    """mastic_tpu/net/ is inside both the robustness and the
    whole-program secret-flow reporting scopes (ISSUE 11): a
    deadline-less read or a secret-bearing error body in the network
    front must be a finding, not a blind spot."""
    from tools.analysis import robustness, secretflow

    for rel in ("mastic_tpu/net/ingest.py",
                "mastic_tpu/net/admission.py",
                "mastic_tpu/net/transport.py",
                "mastic_tpu/net/loadgen.py"):
        assert robustness.in_scope(rel), rel
        assert secretflow.wp_in_scope(rel), rel
    assert robustness.in_scope("tools/loadgen.py")
    assert secretflow.wp_in_scope("tools/loadgen.py")
    assert not robustness.in_scope("mastic_tpu/ops/field_jax.py")


# -- ISSUE 17: the CFG engine, incremental cache, determinism --------

def test_lifetime_and_evloop_files_in_analyzer_scope():
    """The session/network plane the event-loop rewrite lands on is
    inside both new passes' scopes."""
    from tools.analysis import evloop, lifetime

    for rel in ("mastic_tpu/net/transport.py",
                "mastic_tpu/net/ingest.py",
                "mastic_tpu/drivers/session.py",
                "mastic_tpu/drivers/parties.py",
                "tools/party.py", "tools/serve.py",
                "tools/loadgen.py"):
        assert lifetime.in_scope(rel), rel
        assert evloop.in_scope(rel), rel
    assert not lifetime.in_scope("mastic_tpu/ops/field_jax.py")
    assert not evloop.in_scope("mastic_tpu/ops/field_jax.py")


def test_stale_allow_on_cfg_rules_is_flagged():
    """AL002 fires for RL/EV allows too: a leak that got fixed must
    not leave its allow behind (satellite 5)."""
    (findings, suppressed) = run_fixture("al002_rl_bad.py",
                                         "lifetime")
    assert [f.rule for f in findings] == ["AL002"]
    assert suppressed == []


def test_budget_bump_workflow():
    """The budget gate's two directions: growth past the committed
    baseline trips it, and an explicit baseline bump in the same diff
    (the documented workflow) clears it again."""
    (_findings, suppressed) = _tree_run()
    stats = analysis.suppression_stats(suppressed)
    budget = analysis.load_budget()
    over = dict(stats)
    over["total"] = budget["total"] + 1
    assert analysis.check_budget(over, budget), (
        "one allow past the baseline must trip the gate")
    bumped = dict(budget)
    bumped["total"] = budget["total"] + 1
    assert analysis.check_budget(over, bumped) == [], (
        "a baseline bump in the diff must clear the gate")


def _cache_cli(tmp_path, *extra):
    import json
    import os
    import subprocess
    import sys

    env = dict(os.environ)
    env["MASTIC_ANALYSIS_CACHE_DIR"] = str(tmp_path / "cache")
    proc = subprocess.run(
        [sys.executable, "-m", "tools.analysis", "--json", "--stats",
         "--force-scope", "--pass", "lifetime", "--pass", "evloop",
         str(FIXTURES / "rl002_bad.py"),
         str(FIXTURES / "ev001_bad.py"), *extra],
        capture_output=True, text=True, env=env,
        cwd=str(pathlib.Path(__file__).parent.parent))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    return json.loads(proc.stdout)


def test_cache_warm_run_hits_and_is_identical(tmp_path):
    """Satellite 1 acceptance: the second run over unchanged content
    serves every file AND the whole-program layer from the cache
    (hit counts asserted), and its findings are byte-identical to
    the cold run's."""
    import json

    cold = _cache_cli(tmp_path)
    warm = _cache_cli(tmp_path)
    assert cold["cache"] == {"hits": 0, "misses": 2,
                             "program_hit": False}
    assert warm["cache"] == {"hits": 2, "misses": 0,
                             "program_hit": True}
    for key in ("findings", "suppressed", "stats"):
        assert json.dumps(cold[key]) == json.dumps(warm[key]), key
    assert cold["findings"], "the fixtures must produce findings"


def test_no_cache_flag_runs_cold(tmp_path):
    warm_dir = _cache_cli(tmp_path)
    assert warm_dir["cache"]["misses"] == 2
    cold = _cache_cli(tmp_path, "--no-cache")
    assert cold["cache"] is None


def test_cache_invalidates_on_set_change(tmp_path):
    """Changing the analyzed content re-runs exactly the changed part:
    untouched files stay warm, but the whole-program entry (keyed over
    every file's digest) goes cold."""
    base = [FIXTURES / "rl002_bad.py", FIXTURES / "ev001_bad.py"]
    cache = analysis.AnalysisCache(root=tmp_path / "cache")
    analysis.analyze_paths(base, only_passes={"lifetime", "evloop"},
                           force_scope=True, cache=cache)
    assert (cache.hits, cache.misses) == (0, 2)
    cache2 = analysis.AnalysisCache(root=tmp_path / "cache")
    analysis.analyze_paths(base + [FIXTURES / "rl001_bad.py"],
                           only_passes={"lifetime", "evloop"},
                           force_scope=True, cache=cache2)
    assert (cache2.hits, cache2.misses) == (2, 1)
    assert not cache2.program_hit, (
        "a changed file set must invalidate the whole-program entry")


def test_findings_and_sarif_are_deterministically_ordered():
    """Satellite 2: findings sort by (path, line, rule) no matter the
    input path order, and the SARIF results stream interleaves
    suppressed and unsuppressed entries in that same one order with
    repo-relative URIs only."""
    paths = [FIXTURES / "ev001_bad.py", FIXTURES / "rl002_bad.py",
             FIXTURES / "rl001_bad.py"]
    (fwd, _s1) = analysis.analyze_paths(
        paths, only_passes={"lifetime", "evloop"}, force_scope=True)
    (rev, _s2) = analysis.analyze_paths(
        list(reversed(paths)), only_passes={"lifetime", "evloop"},
        force_scope=True)
    keys = [f.key() for f in fwd]
    assert keys == sorted(keys)
    assert keys == [f.key() for f in rev]

    log = _sarif_for(analysis.default_files())
    results = log["runs"][0]["results"]
    sarif_keys = [(r["locations"][0]["physicalLocation"]
                    ["artifactLocation"]["uri"],
                   r["locations"][0]["physicalLocation"]
                    ["region"]["startLine"],
                   r["ruleId"]) for r in results]
    assert sarif_keys == sorted(sarif_keys)
    import json as _json
    dump = _json.dumps(log)
    assert str(analysis.REPO) not in dump, (
        "SARIF must carry repo-relative URIs only")
