"""Known-bad (ISSUE 11, network-front flavor): an HTTP upload
handler whose connection reads never arm a deadline (RB001) — a
client stalling mid-body wedges the handler thread forever."""


class Handler:
    def handle_upload(self):
        (conn, _addr) = self.server.accept()
        header = conn.recv(4)
        return header
