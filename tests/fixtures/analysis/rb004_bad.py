"""Known-bad: unbounded buffer growth (RB004) — a capacity-less
queue and an append loop with no bound or exit."""

import collections
import queue


def make_buffers():
    uploads = queue.Queue()            # no maxsize: unbounded
    pages = collections.deque()        # no maxlen: unbounded
    return (uploads, pages)


def ingest_forever(source, buffered):
    while True:
        buffered.append(source.take())
