"""Unified telemetry layer (ISSUE 7): the one observability substrate
every runtime layer reports through.

Before this package, runtime behavior surfaced as ad-hoc
`RoundMetrics.extra` dicts stamped with incompatible schemas at three
layers and printed by whoever remembered to — there was no way to ask
"why was tenant A's epoch slow" of a running `tools/serve.py` without
a debugger.  The package is four small, zero-dependency modules:

  trace.py     structured spans: monotonic-clock, parent-linked,
               tenant/epoch/round/chunk attributed, ring-buffered in
               memory and appendable as JSONL (`MASTIC_TRACE_FILE`);
               retry/fault/quarantine events land as span events
  registry.py  named counters / gauges / histograms with label sets,
               exported as Prometheus text and a JSON snapshot; label
               cardinality is capped (overflow counted, never OOM)
  devtime.py   device-time attribution: the per-chunk phase timeline
               (upload/compile/dispatch/compute-wait/download/host)
               becomes histogram observations with a compile-vs-
               execute split; `MASTIC_JAX_PROFILE=dir` brackets ONE
               round in jax.profiler trace capture
  schema.py    the ONE versioned schema for the `extra["chunks"]` /
               `extra["mesh"]` / `extra["service"]` / `extra["pipeline"]`
               blocks, validated by `RoundMetrics.validate_extra`
  statusz.py   the live status surface: a stdlib http.server thread
               serving /metrics (Prometheus), /statusz (human text)
               and /varz (JSON), snapshot-under-lock so the single-
               threaded scheduler never races a scrape

Everything is import-cheap and jax-free at module level (the drivers
import this on every round); the tracer and registry are process-wide
singletons so offline bench runs (`bench.py`, `tools/northstar.py`)
and the live service (`tools/serve.py`) emit the same span schema and
the same metric names — USAGE.md "Observability" has the lever table
and curl examples, and `tools/lint.py` check 9 keeps every registered
metric name documented there.
"""

from . import schema, trace  # noqa: F401  (re-exported submodules)
from .registry import get_registry  # noqa: F401
from .trace import get_tracer  # noqa: F401
