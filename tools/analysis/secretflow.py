"""Pass 3 — secret-flow taint: per-file constant-time rules on the
scalar layer, plus whole-program propagation into the service plane.

Per-file scope (SF001/SF002): mastic_tpu/vidpf.py, mastic.py, aes.py,
xof.py — the scalar protocol layer, where the draft's timing-hygiene
expectations live (the batched backend replaces every
secret-dependent choice with a lane select by construction; the
scalar layer is where a branch on a seed-derived bit can actually
leak).

Taint sources (shared by both analyses):
  * parameters whose name marks secret material (seed/key/rand/alpha/
    beta/measurement/input_share and _seed/_key/_rand suffixes);
  * attribute reads of secret node state (.seed, .ctrl, .w,
    .round_keys — the whole-program rules add .verify_key);
  * calls that produce XOF/PRG output or key material (.next,
    .next_vec, .derive_seed, .encrypt_block, .extend, .convert, .gen,
    .get_beta_share);
  * any value computed from a tainted value (calls with tainted
    arguments taint their result — int()/bool() casts preserve
    secrecy).

`len(x)` and `x is None` escape the taint: lengths and presence are
public protocol parameters in every construction here.

Per-file rules:
  SF001  Python branch (`if`/`while`/ternary/`assert`) on a tainted
         value — secret-dependent control flow.
  SF002  subscript whose *index* is tainted — secret-dependent memory
         access (the classic table-lookup timing channel).

Whole-program rules (ISSUE 8) — the taint is propagated across call
boundaries through the call graph (`callgraph.Program`): a tainted
argument taints the callee's parameter, a function whose return value
is tainted taints every resolved call site, iterated to a fixpoint.
Reported over the service plane (mastic_tpu/drivers/, mastic_tpu/obs/,
mastic_tpu/net/, mastic_tpu/metrics.py, tools/serve.py,
tools/loadgen.py — the network front's HTTP error bodies are egress
at internet exposure, ISSUE 11):

  SF003  tainted value reaching a TELEMETRY sink: span attrs/events
         (`event`, `start_span`, `span`, `.set`), registry series
         (label kwargs of counter/gauge/histogram, `.inc`/`.observe`
         values), or `/statusz` rendering — secrets must never be
         scrapeable, traceable, or Prometheus-labelled.

  SF004  tainted data LEAVING THE PROCESS unencoded outside the
         blessed `mastic_tpu/wire.py` codecs: socket sends
         (`send_msg`/`sendall`/`sendto`), file/pipe writes, prints,
         and subprocess argv/env (argv is world-readable in
         /proc/<pid>/cmdline).  A value produced by `wire.*` is
         declassified — the codec layer is the audited egress.

  SF005  tainted value influencing RETRY/BACKOFF TIMING: sleeps,
         `Deadline(...)` budgets, `settimeout`, or a
         `timeout=`/`deadline=` keyword computed from secret-derived
         data — a secret-modulated delay is a remote timing channel.

Known blind spots (documented in USAGE.md): taint does not survive
storage on instance attributes (other than the named secret attrs),
dynamic dispatch past the call graph's resolution cap, getattr, or
callables passed as values.  Real findings are fixed or suppressed
with a written `# mastic-allow`, same as every pass.
"""

import ast

from .core import Finding, call_name, for_target_taints, target_names

PASS_NAME = "secretflow"
WHOLE_PROGRAM = True

RULES = {
    "SF001": "branch on secret-derived value",
    "SF002": "secret-dependent subscript index",
    "SF003": "secret-derived value reaches a telemetry sink",
    "SF004": "secret-derived value leaves the process outside the "
             "wire.py codecs",
    "SF005": "secret-derived value influences retry/backoff timing",
}

SCOPE_FILES = ("mastic_tpu/vidpf.py", "mastic_tpu/mastic.py",
               "mastic_tpu/aes.py", "mastic_tpu/xof.py")

_SECRET_PARAMS = {"seed", "seeds", "key", "keys", "rand", "alpha",
                  "alphas", "beta", "betas", "block", "measurement",
                  "measurements", "input_share", "input_shares",
                  "weight", "verify_key",
                  # ISSUE 14 (mTLS credential handling): TLS private
                  # keys are secrets whether or not the protocol is
                  "private_key", "key_pem", "private_keys"}
_SECRET_SUFFIXES = ("_seed", "_seeds", "_key", "_keys", "_rand",
                    "_rands")
_SECRET_ATTRS = {"seed", "ctrl", "w", "round_keys"}
_SECRET_CALLS = {"next", "next_vec", "derive_seed", "expand_into_vec",
                 "encrypt_block", "extend", "convert", "gen",
                 "get_beta_share"}
_HOST_SAFE = {"len", "isinstance", "range", "enumerate", "hasattr",
              "type", "print", "sorted", "ValueError", "TypeError",
              "set"}


def in_scope(rel: str) -> bool:
    return rel in SCOPE_FILES


def _secret_param(name: str) -> bool:
    return name in _SECRET_PARAMS or name.endswith(_SECRET_SUFFIXES)


def _is_none_test(node: ast.Compare) -> bool:
    return (len(node.ops) == 1
            and isinstance(node.ops[0], (ast.Is, ast.IsNot)))


class _TaintAnalysis:
    SECRET_ATTRS = _SECRET_ATTRS

    def __init__(self, fn, info, findings, inherited=()):
        self.fn = fn
        self.info = info
        self.findings = findings
        self.tainted: set = set(inherited)
        args = fn.args
        for a in args.posonlyargs + args.args + args.kwonlyargs:
            if _secret_param(a.arg):
                self.tainted.add(a.arg)

    def is_tainted(self, node) -> bool:
        if node is None or isinstance(node, ast.Constant):
            return False
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            if node.attr in self.SECRET_ATTRS:
                return True
            return self.is_tainted(node.value)
        if isinstance(node, ast.Subscript):
            return self.is_tainted(node.value)
        if isinstance(node, ast.Call):
            name = call_name(node)
            if isinstance(node.func, ast.Name) and name in _HOST_SAFE:
                return False
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _SECRET_CALLS:
                return True
            return (self.is_tainted(node.func)
                    or any(self.is_tainted(a) for a in node.args)
                    or any(self.is_tainted(k.value)
                           for k in node.keywords))
        if isinstance(node, ast.BinOp):
            return (self.is_tainted(node.left)
                    or self.is_tainted(node.right))
        if isinstance(node, ast.UnaryOp):
            return self.is_tainted(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(self.is_tainted(v) for v in node.values)
        if isinstance(node, ast.Compare):
            if _is_none_test(node):
                return False
            return (self.is_tainted(node.left)
                    or any(self.is_tainted(c) for c in node.comparators))
        if isinstance(node, ast.IfExp):
            return (self.is_tainted(node.body)
                    or self.is_tainted(node.orelse))
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self.is_tainted(e) for e in node.elts)
        if isinstance(node, ast.Starred):
            return self.is_tainted(node.value)
        if isinstance(node, (ast.ListComp, ast.GeneratorExp,
                             ast.SetComp)):
            return (self.is_tainted(node.elt)
                    or any(self.is_tainted(g.iter)
                           for g in node.generators))
        return False

    def _taint_target(self, target):
        self.tainted.update(target_names(target))

    def propagate(self):
        from .tracesafe import iter_scope

        for _ in range(10):
            before = len(self.tainted)
            for node in iter_scope(self.fn):
                if isinstance(node, ast.Assign):
                    if self.is_tainted(node.value):
                        for t in node.targets:
                            self._taint_target(t)
                elif isinstance(node, ast.AugAssign):
                    if self.is_tainted(node.value) \
                            or self.is_tainted(node.target):
                        self._taint_target(node.target)
                elif isinstance(node, ast.AnnAssign):
                    if node.value is not None \
                            and self.is_tainted(node.value):
                        self._taint_target(node.target)
                elif isinstance(node, ast.For):
                    self.tainted.update(for_target_taints(
                        node.target, node.iter, self.is_tainted))
                elif isinstance(node, (ast.ListComp, ast.GeneratorExp,
                                       ast.SetComp, ast.DictComp)):
                    for g in node.generators:
                        self.tainted.update(for_target_taints(
                            g.target, g.iter, self.is_tainted))
            if len(self.tainted) == before:
                break

    def report(self):
        from .tracesafe import iter_scope

        for node in iter_scope(self.fn):
            if isinstance(node, (ast.If, ast.While)) \
                    and self.is_tainted(node.test):
                self._flag("SF001", node,
                           "branch on secret-derived value "
                           f"'{ast.unparse(node.test)[:60]}'")
            elif isinstance(node, ast.IfExp) \
                    and self.is_tainted(node.test):
                self._flag("SF001", node,
                           "ternary on secret-derived value "
                           f"'{ast.unparse(node.test)[:60]}'")
            elif isinstance(node, ast.Assert) \
                    and self.is_tainted(node.test):
                self._flag("SF001", node,
                           "assert on secret-derived value")
            elif isinstance(node, ast.Subscript) \
                    and self.is_tainted(node.slice):
                self._flag("SF002", node,
                           "secret-dependent index "
                           f"'{ast.unparse(node)[:60]}'")
            # Comprehension iterating a secret container with a
            # secret-indexed lookup inside is caught by the Subscript
            # case (the loop target is tainted via propagate()).

    def _flag(self, rule, node, msg):
        self.findings.append(
            Finding(rule, self.info.rel, node.lineno, msg))


def _analyze(fn, info, findings, inherited=()):
    from .tracesafe import iter_scope

    ta = _TaintAnalysis(fn, info, findings, inherited)
    ta.propagate()
    ta.report()
    for node in iter_scope(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _analyze(node, info, findings, set(ta.tainted))


def check(info) -> list:
    findings: list = []

    def visit(body):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _analyze(node, info, findings)
            elif isinstance(node, ast.ClassDef):
                visit(node.body)

    visit(info.tree.body)
    seen = set()
    out = []
    for f in findings:
        if f.key() in seen:
            continue
        seen.add(f.key())
        out.append(f)
    return out


# ====================================================================
# Whole-program secret flow (SF003-SF005, ISSUE 8)
# ====================================================================

# Where the whole-program rules REPORT (taint is tracked everywhere).
# mastic_tpu/net/ since ISSUE 11: the HTTP upload front's error
# bodies and the load generator are process egress at internet
# exposure — they must be PROVEN secret-free, not assumed.
WP_SCOPE_PREFIXES = ("mastic_tpu/drivers/", "mastic_tpu/obs/",
                     "mastic_tpu/net/")
# tools/party.py + tools/certs.py since ISSUE 14: the standalone
# network party holds the verify key it received over mTLS, and the
# cert tooling orbits PRIVATE KEYS — egress there is the worst case.
WP_SCOPE_FILES = ("tools/serve.py", "tools/loadgen.py",
                  "mastic_tpu/metrics.py", "tools/party.py",
                  "tools/certs.py")

# The service plane adds key-binding material to the secret attrs.
_WP_SECRET_ATTRS = _SECRET_ATTRS | {"verify_key"}

# Values produced by the audited codec layer are declassified: data
# may only cross the wire / a file through these.
_BLESSED_MODULES = ("mastic_tpu.wire", "wire")

_TELEMETRY_CALLS = {"event", "start_span", "start_detached_span",
                    "span", "render_statusz"}
_TELEMETRY_METHODS = {"set", "inc", "observe", "set_total"}
_REGISTRY_CTORS = {"counter", "gauge", "histogram"}
_EGRESS_METHODS = {"send_msg", "sendall", "sendto", "write"}
_EGRESS_CALLS = {"print", "Popen", "check_output", "check_call"}
_TIMING_CALLS = {"sleep", "Deadline", "settimeout"}
_TIMING_KWARGS = {"timeout", "deadline"}


def wp_in_scope(rel: str) -> bool:
    return rel.startswith(WP_SCOPE_PREFIXES) or rel in WP_SCOPE_FILES


class _WPTaint(_TaintAnalysis):
    """The interprocedural variant: call results resolve through the
    program's call graph (a resolved callee taints the result only
    when its RETURN is tainted; unresolved calls keep the per-file
    pass's conservative arg-taint heuristic), `wire.*` results are
    declassified, and dicts / f-strings propagate (the service plane
    marshals secrets through both)."""

    SECRET_ATTRS = _WP_SECRET_ATTRS

    def __init__(self, fnode, engine, extra_params=()):
        self.fnode = fnode
        self.engine = engine
        info = engine.program.infos[fnode.rel]
        super().__init__(fnode.node, info, [],
                         inherited=extra_params)
        self._resolved = {id(call): targets
                          for (call, targets) in fnode.callees}

    def is_tainted(self, node) -> bool:
        if isinstance(node, ast.Call):
            if self._blessed(node):
                return False
            targets = self._resolved.get(id(node), ())
            real = [t for t in targets if not t.is_module]
            # Weak (multi-candidate dispatch) resolutions keep the
            # conservative per-file heuristic; a STRONG resolution
            # uses the callee's actual return taint — a clean callee
            # does not launder its arguments into a taint.
            if real and id(node) not in self.fnode.weak_calls:
                if any(t.qual in self.engine.return_taint
                       for t in real):
                    return True
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr in _SECRET_CALLS:
                    return True
                return False
        if isinstance(node, ast.Dict):
            return any(v is not None and self.is_tainted(v)
                       for v in list(node.keys) + list(node.values))
        if isinstance(node, ast.JoinedStr):
            return any(self.is_tainted(v.value)
                       for v in node.values
                       if isinstance(v, ast.FormattedValue))
        return super().is_tainted(node)

    def _blessed(self, call: ast.Call) -> bool:
        name = call_name(call)
        if name.rsplit(".", 1)[0] in _BLESSED_MODULES:
            return True
        targets = self._resolved.get(id(call), ())
        return any(t.module == "mastic_tpu.wire" for t in targets)


class _InterTaint:
    """Fixpoint over the call graph: per-function tainted-parameter
    sets and the tainted-return set."""

    MAX_ROUNDS = 20

    def __init__(self, program):
        self.program = program
        self.param_taint: dict = {}     # qual -> set of param names
        self.return_taint: set = set()  # quals returning taint
        for fn in program.functions.values():
            if fn.is_module:
                continue
            self.param_taint[fn.qual] = {
                p for p in fn.params() if _secret_param(p)}
        self._fixpoint()

    def _analysis(self, fn) -> _WPTaint:
        ta = _WPTaint(fn, self,
                      extra_params=self.param_taint.get(fn.qual, ()))
        ta.propagate()
        return ta

    def _fixpoint(self) -> None:
        """Worklist fixpoint: a function re-analyzes only when its
        tainted-parameter set grew or a callee's return newly turned
        tainted — the classic dataflow scheduling, so the whole-tree
        run costs ~one analysis per function instead of one per
        function per round."""
        from .tracesafe import iter_scope

        fns = {f.qual: f for f in self.program.functions.values()
               if not f.is_module}
        work = list(fns.values())
        queued = set(fns)
        guard = self.MAX_ROUNDS * max(1, len(fns))
        while work and guard > 0:
            guard -= 1
            fn = work.pop()
            queued.discard(fn.qual)

            def enqueue(qual):
                if qual in fns and qual not in queued:
                    queued.add(qual)
                    work.append(fns[qual])

            ta = self._analysis(fn)
            if fn.qual not in self.return_taint:
                for node in iter_scope(fn.node):
                    if isinstance(node, ast.Return) \
                            and node.value is not None \
                            and ta.is_tainted(node.value):
                        self.return_taint.add(fn.qual)
                        for (caller, _call) in fn.callers:
                            if not caller.is_module:
                                enqueue(caller.qual)
                        break
            for (call, targets) in fn.callees:
                if id(call) in fn.weak_calls:
                    continue   # multi-candidate dispatch: do not
                    #            spread taint to every candidate
                for t in targets:
                    if t.is_module:
                        continue
                    if self._spread_args(ta, call, t):
                        enqueue(t.qual)

    def _spread_args(self, ta, call, callee) -> bool:
        params = callee.params()
        if params and params[0] in ("self", "cls"):
            params = params[1:]
        sink = self.param_taint.setdefault(callee.qual, set())
        before = len(sink)
        for (i, arg) in enumerate(call.args):
            if isinstance(arg, ast.Starred):
                continue
            if i < len(params) and ta.is_tainted(arg):
                sink.add(params[i])
        for kw in call.keywords:
            if kw.arg is not None and kw.arg in params \
                    and ta.is_tainted(kw.value):
                sink.add(kw.arg)
        return len(sink) != before


def _call_tail(call: ast.Call) -> str:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


def _wp_sinks(ta: _WPTaint, fn, findings) -> None:
    for (call, _targets) in fn.callees:
        tail = _call_tail(call)
        dotted_name = call_name(call)
        args = [a for a in call.args
                if not isinstance(a, ast.Starred)]
        kwargs = [kw for kw in call.keywords if kw.arg is not None]
        tainted_args = [a for a in args if ta.is_tainted(a)]
        tainted_kwargs = [kw for kw in kwargs
                          if ta.is_tainted(kw.value)]

        # SF005 first: a timing kwarg on ANY call.
        timing_kw = [kw for kw in tainted_kwargs
                     if kw.arg in _TIMING_KWARGS]
        if timing_kw:
            findings.append(Finding(
                "SF005", fn.rel, call.lineno,
                f"secret-derived value sets '{timing_kw[0].arg}=' on "
                f"'{dotted_name[:40]}' — a secret-modulated delay is "
                f"a remote timing channel"))
        if tail in _TIMING_CALLS and (tainted_args
                                      or tainted_kwargs):
            findings.append(Finding(
                "SF005", fn.rel, call.lineno,
                f"secret-derived value reaches timing primitive "
                f"'{dotted_name[:40]}' — retry/backoff schedules "
                f"must not depend on secrets"))
            continue

        if tail in _TELEMETRY_CALLS and (tainted_args
                                         or tainted_kwargs):
            findings.append(Finding(
                "SF003", fn.rel, call.lineno,
                f"secret-derived value recorded by telemetry call "
                f"'{dotted_name[:40]}' — spans/events are scraped, "
                f"ring-buffered and written to trace JSONL"))
            continue
        if tail in _REGISTRY_CTORS and isinstance(
                call.func, ast.Attribute) and tainted_kwargs:
            findings.append(Finding(
                "SF003", fn.rel, call.lineno,
                f"secret-derived value used as a registry label on "
                f"'{dotted_name[:40]}' — labels are exported "
                f"verbatim at /metrics"))
            continue
        if tail in _TELEMETRY_METHODS and isinstance(
                call.func, ast.Attribute) and (tainted_args
                                               or tainted_kwargs):
            findings.append(Finding(
                "SF003", fn.rel, call.lineno,
                f"secret-derived value recorded via "
                f"'.{tail}()' — registry/span state is exported at "
                f"/metrics and /statusz"))
            continue

        egress = (tail in _EGRESS_METHODS and isinstance(
            call.func, ast.Attribute)) \
            or tail in _EGRESS_CALLS \
            or dotted_name in ("os.write", "subprocess.run")
        if egress:
            leak = tainted_args or [
                kw for kw in tainted_kwargs if kw.arg == "env"]
            if leak:
                findings.append(Finding(
                    "SF004", fn.rel, call.lineno,
                    f"secret-derived value leaves the process via "
                    f"'{dotted_name[:40]}' without passing the "
                    f"wire.py codecs (argv/env are world-readable "
                    f"in /proc; files and sockets need the audited "
                    f"encoders)"))


def check_program(program, force_scope: bool = False) -> list:
    engine = _InterTaint(program)
    findings: list = []
    for fn in program.functions.values():
        if fn.is_module:
            continue
        if not force_scope and not wp_in_scope(fn.rel):
            continue
        ta = engine._analysis(fn)
        _wp_sinks(ta, fn, findings)
    seen = set()
    out = []
    for f in findings:
        if f.key() in seen:
            continue
        seen.add(f.key())
        out.append(f)
    return out
