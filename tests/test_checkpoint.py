"""Checkpoint/resume of a heavy-hitters run (SURVEY.md §5).

A run is stopped between levels, serialized to bytes, restored into
fresh objects, and must finish with exactly the result of the
uninterrupted run — including the incremental carries (the
cache-across-rounds state the reference names at
/root/reference/poc/vidpf.py:243-245).
"""

import pytest

pytestmark = pytest.mark.slow

import numpy as np

from mastic_tpu import MasticCount
from mastic_tpu.backend.incremental import (carry_from_arrays,
                                            carry_to_arrays)
from mastic_tpu.drivers import (HeavyHittersRun, compute_heavy_hitters,
                                get_reports_from_measurements)

BITS = 4
CTX = b"checkpoint test"
VERIFY_KEY = bytes(range(32))
THRESHOLDS = {"default": 2}


def _reports(mastic):
    values = [0b1001, 0b0000, 0b0000, 0b1001, 0b1100, 0b0011, 0b1111,
              0b1111]
    measurements = [
        (mastic.vidpf.test_index_from_int(v, BITS), 1) for v in values
    ]
    return get_reports_from_measurements(mastic, CTX, measurements)


def test_stop_restore_matches_uninterrupted():
    mastic = MasticCount(BITS)
    reports = _reports(mastic)
    want = compute_heavy_hitters(mastic, CTX, THRESHOLDS, reports,
                                 verify_key=VERIFY_KEY)
    assert want  # non-trivial example

    for stop_after in (1, 2):
        run = HeavyHittersRun(mastic, CTX, THRESHOLDS, reports,
                              verify_key=VERIFY_KEY)
        for _ in range(stop_after):
            assert run.step()
        blob = run.to_bytes()
        del run

        resumed = HeavyHittersRun.from_bytes(
            mastic, CTX, THRESHOLDS, reports, VERIFY_KEY, blob)
        while resumed.step():
            pass
        assert resumed.result() == want


def test_checkpoint_rejects_mismatched_store():
    mastic = MasticCount(BITS)
    reports = _reports(mastic)
    run = HeavyHittersRun(mastic, CTX, THRESHOLDS, reports,
                          verify_key=VERIFY_KEY)
    run.step()
    blob = run.to_bytes()
    try:
        HeavyHittersRun.from_bytes(mastic, CTX, THRESHOLDS,
                                   reports[:-1], VERIFY_KEY, blob)
        raise AssertionError("expected ValueError")
    except ValueError:
        pass
    # A different verify_key (or ctx) must fail loudly, not silently
    # reject every report.
    other_key = bytes(31) + b"\x01"
    try:
        HeavyHittersRun.from_bytes(mastic, CTX, THRESHOLDS, reports,
                                   other_key, blob)
        raise AssertionError("expected ValueError")
    except ValueError:
        pass


def test_carry_arrays_roundtrip():
    mastic = MasticCount(BITS)
    reports = _reports(mastic)
    run = HeavyHittersRun(mastic, CTX, THRESHOLDS, reports,
                          verify_key=VERIFY_KEY)
    run.step()
    carry = run.runner.carries[0]
    restored = carry_from_arrays(carry_to_arrays(carry))
    for (a, b) in zip(carry, restored):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_batch_built_run_checkpoints_and_restores():
    """A run built from a marshalled batch (no scalar report list —
    the fleet-scale ingestion path tools/northstar.py uses) must
    checkpoint and restore bit-identically with the same batch passed
    back, and must refuse a restore with neither reports nor batch."""
    from mastic_tpu.backend.mastic_jax import BatchedMastic

    mastic = MasticCount(BITS)
    reports = _reports(mastic)
    want = compute_heavy_hitters(mastic, CTX, THRESHOLDS, reports,
                                 verify_key=VERIFY_KEY)
    batch = BatchedMastic(mastic).marshal_reports(reports)

    run = HeavyHittersRun(mastic, CTX, THRESHOLDS, None,
                          verify_key=VERIFY_KEY, batch=batch)
    assert run.step()
    blob = run.to_bytes()
    del run

    try:
        HeavyHittersRun.from_bytes(mastic, CTX, THRESHOLDS, None,
                                   VERIFY_KEY, blob)
        raise AssertionError("expected ValueError")
    except ValueError:
        pass

    resumed = HeavyHittersRun.from_bytes(
        mastic, CTX, THRESHOLDS, None, VERIFY_KEY, blob, batch=batch)
    while resumed.step():
        pass
    assert resumed.result() == want
