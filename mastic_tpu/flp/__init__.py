from .flp import FlpBBCGGI19, Gadget, Mul, ParallelSum, PolyEval, Valid
from .circuits import Count, Histogram, MultihotCountVec, Sum, SumVec

__all__ = [
    "FlpBBCGGI19", "Gadget", "Mul", "ParallelSum", "PolyEval", "Valid",
    "Count", "Histogram", "MultihotCountVec", "Sum", "SumVec",
]
