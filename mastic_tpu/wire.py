"""Inter-party wire codec: byte encodings and decoders for every
channel of the protocol.

The encoders are shared with the conformance harness
(mastic_tpu.testvec_codec — the layouts are pinned by the reference's
test vectors, /root/reference/test_vec/mastic/*.json; reference wire
types at /root/reference/poc/mastic.py:31-49, encoders :512-559).
This module adds the decoders (the reference PoC never parses its own
encodings — parties pass Python objects in-process) plus the framing
used by the process-separated parties (drivers/parties.py).

All message lengths are static given (instantiation, agg_id,
agg_param) — the size formulas of SURVEY.md §2.4, asserted by
tests/test_wire.py and drivers/communication.py.
"""

import struct
from typing import IO, Optional

from .mastic import (Mastic, MasticAggParam, MasticInputShare,
                     MasticPrepMessage, MasticPrepShare)
from .vidpf import CorrectionWord
from .xof import XofTurboShake128

SEED_SIZE = XofTurboShake128.SEED_SIZE
KEY_SIZE = 16
PROOF_SIZE = 32


# -- sizes (SURVEY.md §2.4, verified byte-exact vs test_vec/) --------

def input_share_size(mastic: Mastic, agg_id: int) -> int:
    use_jr = mastic.flp.JOINT_RAND_LEN > 0
    if agg_id == 0:
        size = KEY_SIZE + mastic.flp.PROOF_LEN * mastic.field.ENCODED_SIZE
        if use_jr:
            size += 2 * SEED_SIZE
    else:
        size = KEY_SIZE + SEED_SIZE
        if use_jr:
            size += SEED_SIZE
    return size


def prep_share_size(mastic: Mastic, agg_param: MasticAggParam) -> int:
    (_level, _prefixes, do_weight_check) = agg_param
    size = PROOF_SIZE
    if do_weight_check:
        if mastic.flp.JOINT_RAND_LEN > 0:
            size += SEED_SIZE
        size += mastic.flp.VERIFIER_LEN * mastic.field.ENCODED_SIZE
    return size


def agg_share_size(mastic: Mastic, agg_param: MasticAggParam) -> int:
    (_level, prefixes, _wc) = agg_param
    return len(prefixes) * (1 + mastic.flp.OUTPUT_LEN) \
        * mastic.field.ENCODED_SIZE


def report_size(mastic: Mastic, agg_id: int) -> int:
    """One aggregator's view of an upload blob: nonce ‖ public share
    ‖ that party's input share (decode_report refuses other sizes)."""
    return (mastic.NONCE_SIZE + public_share_size(mastic)
            + input_share_size(mastic, agg_id))


def public_share_size(mastic: Mastic) -> int:
    """ceil(2*BITS/8) packed ctrl bits + per-level seed, payload CW
    and proof CW (SURVEY.md §2.4; encoder mastic_tpu/vidpf.py:335)."""
    bits = mastic.vidpf.BITS
    return (2 * bits + 7) // 8 + bits * (
        KEY_SIZE + PROOF_SIZE
        + mastic.vidpf.VALUE_LEN * mastic.field.ENCODED_SIZE)


# -- decoders (inverses of testvec_codec's encoders) -----------------

def decode_input_share(mastic: Mastic, agg_id: int,
                       encoded: bytes) -> MasticInputShare:
    if len(encoded) != input_share_size(mastic, agg_id):
        raise ValueError(
            f"input share has incorrect length: got {len(encoded)}, "
            f"want {input_share_size(mastic, agg_id)}")
    use_jr = mastic.flp.JOINT_RAND_LEN > 0
    (key, rest) = (encoded[:KEY_SIZE], encoded[KEY_SIZE:])
    proof_share = None
    seed = None
    if agg_id == 0:
        plen = mastic.flp.PROOF_LEN * mastic.field.ENCODED_SIZE
        try:
            proof_share = mastic.field.decode_vec(rest[:plen])
        except ValueError as exc:
            raise ValueError(f"input share: proof share: {exc}")
        rest = rest[plen:]
        if use_jr:
            (seed, rest) = (rest[:SEED_SIZE], rest[SEED_SIZE:])
    else:
        (seed, rest) = (rest[:SEED_SIZE], rest[SEED_SIZE:])
    peer_part = rest[:SEED_SIZE] if use_jr else None
    return (key, proof_share, seed, peer_part)


def decode_public_share(mastic: Mastic,
                        encoded: bytes) -> list[CorrectionWord]:
    return mastic.vidpf.decode_public_share(encoded)


def decode_prep_share(mastic: Mastic, agg_param: MasticAggParam,
                      encoded: bytes) -> MasticPrepShare:
    if len(encoded) != prep_share_size(mastic, agg_param):
        raise ValueError(
            f"prep share has incorrect length: got {len(encoded)}, "
            f"want {prep_share_size(mastic, agg_param)}")
    (_level, _prefixes, do_weight_check) = agg_param
    (eval_proof, rest) = (encoded[:PROOF_SIZE], encoded[PROOF_SIZE:])
    verifier = None
    jr_part = None
    if do_weight_check:
        if mastic.flp.JOINT_RAND_LEN > 0:
            (jr_part, rest) = (rest[:SEED_SIZE], rest[SEED_SIZE:])
        try:
            verifier = mastic.field.decode_vec(rest)
        except ValueError as exc:
            raise ValueError(f"prep share: verifier: {exc}")
    return (eval_proof, verifier, jr_part)


def decode_prep_msg(mastic: Mastic, agg_param: MasticAggParam,
                    encoded: bytes) -> MasticPrepMessage:
    (_level, _prefixes, do_weight_check) = agg_param
    if do_weight_check and mastic.flp.JOINT_RAND_LEN > 0:
        if len(encoded) != SEED_SIZE:
            raise ValueError("prep message has incorrect length")
        return encoded
    if encoded != b"":
        raise ValueError("unexpected prep message payload")
    return None


def decode_agg_share(mastic: Mastic, agg_param: MasticAggParam,
                     encoded: bytes) -> list:
    if len(encoded) != agg_share_size(mastic, agg_param):
        raise ValueError(
            f"aggregate share has incorrect length: got "
            f"{len(encoded)}, want {agg_share_size(mastic, agg_param)}")
    try:
        return mastic.field.decode_vec(encoded)
    except ValueError as exc:
        raise ValueError(f"aggregate share: {exc}")


# -- report upload framing -------------------------------------------

def encode_report(mastic: Mastic, agg_id: int, nonce: bytes,
                  public_share: list[CorrectionWord],
                  input_share: MasticInputShare) -> bytes:
    """One aggregator's view of an upload: nonce || public share ||
    that party's input share (all fixed-size for the instantiation)."""
    from .testvec_codec import encode_input_share
    return nonce + mastic.vidpf.encode_public_share(public_share) \
        + encode_input_share(mastic, input_share)


def decode_report(mastic: Mastic, agg_id: int, encoded: bytes) -> tuple:
    if len(encoded) != report_size(mastic, agg_id):
        raise ValueError(
            f"report for aggregator {agg_id} has incorrect length: "
            f"got {len(encoded)}, want {report_size(mastic, agg_id)}")
    nonce = encoded[:mastic.NONCE_SIZE]
    rest = encoded[mastic.NONCE_SIZE:]
    ps_size = public_share_size(mastic)
    try:
        public_share = mastic.vidpf.decode_public_share(rest[:ps_size])
    except ValueError as exc:
        raise ValueError(f"report: public share: {exc}")
    try:
        input_share = decode_input_share(mastic, agg_id,
                                         rest[ps_size:])
    except ValueError as exc:
        raise ValueError(f"report: {exc}")
    return (nonce, public_share, input_share)


# -- stream framing for the party channels ---------------------------

def send_msg(stream: IO[bytes], payload: bytes) -> None:
    stream.write(struct.pack("<I", len(payload)) + payload)
    stream.flush()


def recv_msg(stream: IO[bytes]) -> Optional[bytes]:
    header = stream.read(4)
    if len(header) < 4:
        return None
    (length,) = struct.unpack("<I", header)
    payload = stream.read(length)
    if len(payload) < length:
        raise EOFError("truncated message")
    return payload


def frame(payload: bytes) -> bytes:
    """Length-prefix a message for embedding in a larger blob."""
    return struct.pack("<I", len(payload)) + payload


def unframe(buf: bytes) -> tuple[bytes, bytes]:
    """Pop one length-prefixed message: -> (payload, rest).  A
    truncated header or payload raises (returning a silently-short
    payload would turn a framing error into a content mismatch)."""
    if len(buf) < 4:
        raise ValueError("truncated frame header")
    (length,) = struct.unpack("<I", buf[:4])
    if len(buf) < 4 + length:
        raise ValueError("truncated frame payload")
    return (buf[4:4 + length], buf[4 + length:])
