"""The ONE versioned schema for `RoundMetrics.extra` (ISSUE 7
satellite: unify the three `extra` schemas).

Before this module, three layers stamped `extra` blocks with
incompatible key sets: the chunked heavy-hitters runner, the resident
runner and the chunked attribute-metrics round each invented their
own `pipeline` record (some with `round_wall_ms`, some without; the
attribute path's chunk records lacked `wall_ms` entirely), and the
collector service appended a fourth shape on top.  Nothing validated
any of them, so a consumer (bench JSON diffing, the statusz last-round
timeline) had to special-case every producer.

This module is the contract:

* `SCHEMA_VERSION` — bumped whenever a required key is added or a
  type changes; stamped into `extra["schema"]` by `stamp()`;
* required key sets per block (chunks / pipeline / mesh / service) —
  the INTERSECTION every producer must stamp.  Producers may add
  optional keys (the chunked runner's node-eval rates, the resident
  runner's phase record), but serial-fallback and pipelined rounds of
  one producer must stamp the SAME required set, which
  `validate_extra` enforces;
* `validate_extra(extra)` — returns a list of problem strings (empty
  when valid); `stamp(extra)` raises on problems and writes the
  version.  `RoundMetrics.validate_extra()` delegates here, and every
  driver calls it right before appending the metrics record, so a
  drifting producer fails its own tests instead of surprising a
  consumer.

Block shapes (all times float milliseconds):

  extra["chunks"]   [ {chunk, stage_start_ms, stage_end_ms,
                       collect_start_ms, collect_end_ms, phases,
                       host_syncs, reports, wall_ms, ...} ]
                    phases holds at least {upload_ms, dispatch_ms,
                    compute_wait_ms, download_ms, host_ms}
                    (compile_ms where an AOT cache is in play)
  extra["pipeline"] {mode, fallback, round_wall_ms,
                     overlap_efficiency, ...}
                    mode in {"pipelined", "serial",
                    "resident-deferred"}; fallback is None or the
                    named degrade reason
  extra["mesh"]     {report_shards, psum_bytes_per_round,
                     shard_wait_skew_ms_p50, shard_wait_skew_ms_max,
                     ...}
  extra["service"]  {tenant, epoch, sched_overhead_ms,
                     buffered_reports, pending_epochs}
  extra["artifacts"] {store, hits, inline_compiles, ...}
                    the AOT artifact-store stamp (r14): store path
                    (None = no store armed), per-round artifact
                    hits vs inline compiles
"""

from typing import Optional

SCHEMA_VERSION = 1

CHUNK_REQUIRED = frozenset((
    "chunk", "stage_start_ms", "stage_end_ms", "collect_start_ms",
    "collect_end_ms", "phases", "host_syncs", "reports", "wall_ms"))

PHASE_REQUIRED = frozenset((
    "upload_ms", "dispatch_ms", "compute_wait_ms", "download_ms",
    "host_ms"))

PIPELINE_REQUIRED = frozenset((
    "mode", "fallback", "round_wall_ms", "overlap_efficiency"))

PIPELINE_MODES = ("pipelined", "serial", "resident-deferred")

MESH_REQUIRED = frozenset((
    "report_shards", "psum_bytes_per_round",
    "shard_wait_skew_ms_p50", "shard_wait_skew_ms_max"))

SERVICE_REQUIRED = frozenset((
    "tenant", "epoch", "sched_overhead_ms", "buffered_reports",
    "pending_epochs"))

# The AOT artifact-store stamp (drivers/artifacts.py): per-round
# artifact hits vs inline compiles, and which store served them
# (None = no store armed).  Producers with a ProgramCache (the two
# heavy-hitters runners) stamp it every round.
ARTIFACTS_REQUIRED = frozenset((
    "store", "hits", "inline_compiles"))


def _missing(block: dict, required: frozenset) -> Optional[str]:
    missing = sorted(required - set(block))
    return ", ".join(missing) if missing else None


def _num(x) -> bool:
    return isinstance(x, (int, float)) and not isinstance(x, bool)


def validate_extra(extra: dict) -> list:
    """Problems with the observability blocks of one metrics record
    (empty list = valid).  Only the four owned blocks are checked;
    other extra keys (round_wall_ms, memory, quarantine, ...) are the
    producers' own."""
    problems: list = []
    chunks = extra.get("chunks")
    if chunks is not None:
        if not isinstance(chunks, list):
            problems.append("chunks: must be a list of chunk records")
        else:
            for (i, rec) in enumerate(chunks):
                miss = _missing(rec, CHUNK_REQUIRED)
                if miss:
                    problems.append(f"chunks[{i}]: missing {miss}")
                    continue
                phases = rec["phases"]
                if not isinstance(phases, dict):
                    problems.append(f"chunks[{i}].phases: must be a "
                                    f"dict of phase -> ms")
                    continue
                miss = _missing(phases, PHASE_REQUIRED)
                if miss:
                    problems.append(
                        f"chunks[{i}].phases: missing {miss}")
                bad = [k for (k, v) in phases.items() if not _num(v)]
                if bad:
                    problems.append(
                        f"chunks[{i}].phases: non-numeric "
                        f"{sorted(bad)}")
    pipeline = extra.get("pipeline")
    if pipeline is not None:
        miss = _missing(pipeline, PIPELINE_REQUIRED)
        if miss:
            problems.append(f"pipeline: missing {miss}")
        else:
            if pipeline["mode"] not in PIPELINE_MODES:
                problems.append(
                    f"pipeline.mode: {pipeline['mode']!r} not in "
                    f"{PIPELINE_MODES}")
            fb = pipeline["fallback"]
            if fb is not None and not isinstance(fb, str):
                problems.append("pipeline.fallback: must be None or "
                                "the named degrade reason")
            if not _num(pipeline["round_wall_ms"]):
                problems.append("pipeline.round_wall_ms: non-numeric")
    mesh = extra.get("mesh")
    if mesh is not None:
        miss = _missing(mesh, MESH_REQUIRED)
        if miss:
            problems.append(f"mesh: missing {miss}")
    artifacts = extra.get("artifacts")
    if artifacts is not None:
        miss = _missing(artifacts, ARTIFACTS_REQUIRED)
        if miss:
            problems.append(f"artifacts: missing {miss}")
        else:
            store = artifacts["store"]
            if store is not None and not isinstance(store, str):
                problems.append("artifacts.store: must be None or "
                                "the store path")
            for field in ("hits", "inline_compiles"):
                if not _num(artifacts[field]):
                    problems.append(f"artifacts.{field}: non-numeric")
    service = extra.get("service")
    if service is not None:
        miss = _missing(service, SERVICE_REQUIRED)
        if miss:
            problems.append(f"service: missing {miss}")
        elif not isinstance(service["tenant"], str):
            problems.append("service.tenant: must be the tenant name")
    version = extra.get("schema")
    if version is not None and version != SCHEMA_VERSION:
        problems.append(f"schema: version {version} != "
                        f"{SCHEMA_VERSION}")
    return problems


def stamp(extra: dict) -> None:
    """Validate and version-stamp one metrics record's extra dict;
    raises ValueError naming every problem (a drifting producer must
    fail its own round, not a downstream consumer)."""
    problems = validate_extra(extra)
    if problems:
        raise ValueError("RoundMetrics.extra schema violation: "
                         + "; ".join(problems))
    extra["schema"] = SCHEMA_VERSION
