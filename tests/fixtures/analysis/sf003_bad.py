"""SF003 bad fixture: a seed-derived value lands in a span event."""


def record_round(tracer, seed):
    tracer.event("round", seed_head=seed[0])
