"""mastic_tpu: a TPU-native framework for the Mastic VDAF.

Scalar CPU reference layer (byte-exact against the reference
conformance vectors):
  common, field, keccak, aes, xof, dst, flp, vidpf, vdaf, mastic

TPU execution layer (JAX / XLA / Pallas):
  ops       batched crypto + field kernels
  backend   level-synchronous batched VIDPF/Mastic engine
  parallel  mesh sharding, collectives, multi-chip scaling
  drivers   heavy hitters, attribute-based metrics, benchmarks
"""

from .mastic import (Mastic, MasticCount, MasticHistogram,
                     MasticMultihotCountVec, MasticSum, MasticSumVec)
from .vidpf import Vidpf

__version__ = "0.1.0"

__all__ = [
    "Mastic", "MasticCount", "MasticSum", "MasticSumVec",
    "MasticHistogram", "MasticMultihotCountVec", "Vidpf",
]
