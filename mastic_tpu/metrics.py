"""Round metrics: the observability layer (SURVEY.md §5).

Every aggregation round produces one `RoundMetrics` record with

* verdict counters — reports accepted, and rejected attributed to the
  FIRST failing check in protocol order (VIDPF eval proof, then FLP
  weight check, then joint-rand confirmation — the order of
  prep_shares_to_prep / prep_next, reference mastic.py:339-377);
* structural op counters — node evaluations, fixed-key AES blocks,
  Keccak node-proof permutations.  These are *derived from the public
  round structure* (prefix set, level, instantiation), not sampled
  from the device: the batched programs evaluate exactly the
  scheduled grid, so the counts are exact by construction and the
  op-model test (tests/test_metrics.py) locks them against an
  independent recount (SURVEY.md §3.2's model);
* bytes per channel — upload, prep share broadcast, prep messages,
  aggregate shares, from the wire size formulas (mastic_tpu.wire,
  themselves conformance-locked).

The drivers accumulate these per level; heavy-hitters exposes them as
`HeavyHittersRun.metrics`.  The collector service
(`drivers/service.py`) adds `ServiceCounters` — the per-tenant
admission / backpressure / epoch ledger, with the same
never-silent-degradation contract the r8 session counters set.

Since ISSUE 7 both records feed the unified telemetry layer
(`mastic_tpu/obs/`): `ServiceCounters` increments route through
`inc()` / the `bump_*` helpers, which mirror into the process-wide
metrics registry (tenant-labelled Prometheus series), and
`RoundMetrics.validate_extra()` holds every producer of the
`extra["chunks"]` / `extra["pipeline"]` / `extra["mesh"]` /
`extra["service"]` blocks to the ONE versioned schema
(`obs/schema.py`).
"""

import threading
from dataclasses import asdict, dataclass, field

import numpy as np

from .obs.registry import get_registry


@dataclass
class RoundMetrics:
    level: int
    frontier_width: int          # number of candidate prefixes
    padded_width: int            # device grid width (incremental) or
    #                              total child-grid nodes (from-root)
    reports_total: int
    accepted: int = 0
    rejected_eval_proof: int = 0
    rejected_weight_check: int = 0
    rejected_joint_rand: int = 0
    rejected_fallback: int = 0   # rejected by the scalar fallback path
    #                              (check attribution unknown there)
    xof_fallbacks: int = 0       # lanes recomputed via the scalar path
    # session fault-tolerance counters (drivers/parties.py; session-
    # cumulative so degradation is observable, not silent):
    timeouts: int = 0            # deadline expiries attributed so far
    retries: int = 0             # idempotent-exchange / round retries
    quarantined: int = 0         # reports rejected at upload decode
    respawns: int = 0            # party pairs killed and respawned
    # transport-recovery counters (ISSUE 14, reliable TCP/mTLS links):
    reconnects: int = 0          # links redialed + resumed mid-session
    replayed_frames: int = 0     # frames redelivered after reconnects
    #                              (deduped by seq: replay ≠ duplicate)
    # structural op counts, summed over both aggregators:
    node_evals: int = 0
    aes_extend_blocks: int = 0
    aes_convert_blocks: int = 0
    keccak_node_proofs: int = 0
    # bytes per channel for this round:
    bytes_upload: int = 0        # client -> one aggregator (x2 parties)
    bytes_prep_shares: int = 0   # aggregator <-> aggregator
    bytes_prep_msgs: int = 0     # leader -> helper
    bytes_agg_shares: int = 0    # aggregators -> collector
    extra: dict = field(default_factory=dict)

    def as_dict(self) -> dict:
        return asdict(self)

    def validate_extra(self) -> None:
        """Hold this record's observability blocks to the unified
        schema and stamp `extra["schema"]` (obs/schema.py).  Every
        stamping driver calls this before appending the record, so a
        producer that drifts from the schema fails its own round."""
        from .obs import schema

        schema.stamp(self.extra)


# ServiceCounters field -> (registry series, outcome label).  Fields
# not listed either have no Prometheus twin or are owned by another
# producer (`rounds` is fed per round by obs/devtime.observe_round —
# mirroring it here too would double-count the series).
_SERVICE_SERIES = {
    "admitted": ("mastic_reports_admitted_total", None),
    "pages_sealed": ("mastic_pages_sealed_total", None),
    "pages_corrupt": ("mastic_pages_corrupt_total", None),
    "deadline_misses": ("mastic_deadline_misses_total", None),
    "epochs_completed": ("mastic_epochs_total", "completed"),
    "epochs_truncated": ("mastic_epochs_total", "truncated"),
    "epochs_failed": ("mastic_epochs_total", "failed"),
    "epochs_refused": ("mastic_epochs_total", "refused"),
    "epochs_started": ("mastic_epochs_total", "started"),
}


@dataclass
class ServiceCounters:
    """Per-tenant ledger of the collector service's defensive
    decisions (drivers/service.py).  Everything the service refuses,
    drops, truncates, or retries lands here — backpressure and
    degradation are surfaced, never silent.  `shed_reasons` /
    `quarantine_reasons` break the totals down by policy / reason
    name (the r8 reason-code taxonomy plus the service's
    page-corrupt and tenant-quarantined entries).

    ISSUE 7: increments route through `inc()` and the `bump_*`
    helpers, which mirror into the telemetry registry
    (tenant-labelled `mastic_*` series, exported at `/metrics`); the
    dataclass remains the snapshot/serialization ledger.
    `export_registry()` republishes the persisted totals after a
    snapshot restore so a resumed service's series continue from
    where the crashed process left them.

    ISSUE 10: the concurrent ingest front increments these from its
    worker threads while the scheduler thread increments and
    snapshots them, so every mutation (and `as_dict`, which iterates
    the reason dicts) runs under the ledger's own lock.  The registry
    mirror calls stay OUTSIDE the lock — the registry locks itself,
    and nesting the two would couple their lock orders for nothing."""

    tenant: str = ""             # registry label; "" = unattributed
    admitted: int = 0
    quarantined: int = 0         # reports refused at the door
    shed: int = 0                # reports dropped by backpressure
    pages_sealed: int = 0
    pages_corrupt: int = 0       # digest-check failures (detected)
    epochs_started: int = 0
    epochs_completed: int = 0
    epochs_truncated: int = 0    # deadline-missed, degraded output
    epochs_failed: int = 0       # supervision gave up after retries
    epochs_refused: int = 0      # begin_epoch hit the queue bound
    deadline_misses: int = 0
    rounds: int = 0              # scheduler quanta executed
    resumes: int = 0             # snapshot restores of this tenant
    quarantine_reasons: dict = field(default_factory=dict)
    shed_reasons: dict = field(default_factory=dict)

    def __post_init__(self):
        # Not a dataclass field: the lock never serializes (asdict
        # walks fields only) and a restored ledger builds a fresh one.
        self._lock = threading.Lock()

    def inc(self, name: str, n: int = 1) -> None:
        """Increment one counter field, mirroring into the registry
        when the field has a Prometheus twin (_SERVICE_SERIES)."""
        with self._lock:
            setattr(self, name, getattr(self, name) + n)
        series = _SERVICE_SERIES.get(name)
        if series is not None:
            (metric, outcome) = series
            labels = {"tenant": self.tenant}
            if outcome is not None:
                labels["outcome"] = outcome
            get_registry().counter(metric, **labels).inc(n)

    def bump_quarantine(self, reason: str, n: int = 1) -> None:
        with self._lock:
            self.quarantine_reasons[reason] = \
                self.quarantine_reasons.get(reason, 0) + n
        get_registry().counter("mastic_reports_quarantined_total",
                               tenant=self.tenant,
                               reason=reason).inc(n)

    def bump_shed(self, reason: str, n: int = 1) -> None:
        with self._lock:
            self.shed_reasons[reason] = \
                self.shed_reasons.get(reason, 0) + n
        get_registry().counter("mastic_reports_shed_total",
                               tenant=self.tenant,
                               reason=reason).inc(n)

    def export_registry(self) -> None:
        """(Re)publish this ledger's totals into the registry —
        called at tenant construction (so every tenant's series exist
        from boot, at zero) and after a snapshot restore (so the
        series continue from the persisted totals instead of
        restarting at zero)."""
        reg = get_registry()
        for (name, (metric, outcome)) in _SERVICE_SERIES.items():
            labels = {"tenant": self.tenant}
            if outcome is not None:
                labels["outcome"] = outcome
            reg.counter(metric, **labels).set_total(
                getattr(self, name))
        for (reason, n) in self.quarantine_reasons.items():
            reg.counter("mastic_reports_quarantined_total",
                        tenant=self.tenant,
                        reason=reason).set_total(n)
        for (reason, n) in self.shed_reasons.items():
            reg.counter("mastic_reports_shed_total",
                        tenant=self.tenant,
                        reason=reason).set_total(n)
        reg.counter("mastic_rounds_total",
                    tenant=self.tenant).set_total(self.rounds)
        reg.counter("mastic_session_retries_total",
                    tenant=self.tenant).inc(0)
        reg.gauge("mastic_buffered_reports",
                  tenant=self.tenant).set(0)
        reg.gauge("mastic_pending_epochs",
                  tenant=self.tenant).set(0)

    def as_dict(self) -> dict:
        # Under the lock: asdict deep-copies the reason dicts, and an
        # ingest worker bumping one mid-iteration would otherwise
        # tear the snapshot (RuntimeError at best, torn ledger at
        # worst).
        with self._lock:
            return asdict(self)

    @classmethod
    def from_dict(cls, data: dict) -> "ServiceCounters":
        return cls(**data)


def attribute_rejections(metrics: RoundMetrics, eval_proof_ok,
                         weight_check_ok=None,
                         joint_rand_ok=None,
                         device_ok=None) -> np.ndarray:
    """Fill the verdict counters from per-report check masks; returns
    the combined accept mask.  Attribution is to the first failing
    check in protocol order.  Lanes where `device_ok` is False carried
    garbage through the device checks (XOF rejection sampling fired);
    they are excluded here and attributed by the caller after the
    scalar fallback resolves them (rejected_fallback)."""
    eval_proof_ok = np.asarray(eval_proof_ok, bool)
    valid = (np.ones_like(eval_proof_ok) if device_ok is None
             else np.asarray(device_ok, bool))
    accept = eval_proof_ok & valid
    metrics.rejected_eval_proof = int((valid & ~eval_proof_ok).sum())
    if weight_check_ok is not None:
        weight_check_ok = np.asarray(weight_check_ok, bool)
        metrics.rejected_weight_check = int(
            (valid & eval_proof_ok & ~weight_check_ok).sum())
        accept &= weight_check_ok
    if joint_rand_ok is not None:
        joint_rand_ok = np.asarray(joint_rand_ok, bool)
        metrics.rejected_joint_rand = int((accept & ~joint_rand_ok).sum())
        accept &= joint_rand_ok
    metrics.accepted = int(accept.sum())
    return accept


def count_round_ops(metrics: RoundMetrics, mastic, num_reports: int,
                    nodes_evaluated: int,
                    include_key_setup: bool = False) -> None:
    """Structural op counts for one aggregator's round, doubled for
    the pair (SURVEY.md §3.2: per node eval = 1 extend block + 1 +
    ceil(VALUE_LEN*elem/16) convert blocks + 1 node-proof
    permutation).  `nodes_evaluated` is the per-report child-node
    count this round's program materializes."""
    payload_bytes = mastic.vidpf.VALUE_LEN * mastic.field.ENCODED_SIZE
    convert_blocks = 1 + (payload_bytes + 15) // 16
    per_agg = num_reports * nodes_evaluated
    metrics.node_evals = 2 * per_agg
    # extend: one 2-block AES call per parent = 1 block per child.
    metrics.aes_extend_blocks = 2 * per_agg
    metrics.aes_convert_blocks = 2 * per_agg * convert_blocks
    metrics.keccak_node_proofs = 2 * per_agg
    if include_key_setup:
        metrics.extra["aes_key_schedules"] = 4 * num_reports
        metrics.extra["fixed_key_derivations"] = 4 * num_reports


def count_round_bytes(metrics: RoundMetrics, mastic, agg_param,
                      num_reports: int) -> None:
    from . import wire

    use_jr = mastic.flp.JOINT_RAND_LEN > 0
    (_level, _prefixes, do_weight_check) = agg_param
    # Uploads are paid once per collection, on the round the reports
    # enter it — which both drivers mark with the weight check (level 0
    # of heavy hitters; the single attribute-metrics round).
    if do_weight_check:
        metrics.bytes_upload = num_reports * upload_bytes(mastic)
    metrics.bytes_prep_shares = \
        2 * num_reports * wire.prep_share_size(mastic, agg_param)
    if do_weight_check and use_jr:
        metrics.bytes_prep_msgs = num_reports * wire.SEED_SIZE
    metrics.bytes_agg_shares = \
        2 * wire.agg_share_size(mastic, agg_param)


def upload_bytes(mastic) -> int:
    """Per-report upload size to ONE aggregator pair: public share +
    both input shares (SURVEY.md §2.4 formulas)."""
    from . import wire

    bits = mastic.vidpf.BITS
    elem = mastic.field.ENCODED_SIZE
    public = (2 * bits + 7) // 8 + bits * (16 + 32) \
        + bits * mastic.vidpf.VALUE_LEN * elem
    return public + wire.input_share_size(mastic, 0) \
        + wire.input_share_size(mastic, 1)
