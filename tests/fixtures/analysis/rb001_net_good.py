"""Known-good twin of rb001_net_bad: the connection is deadline-
bounded before any read (the net/ingest.py setup() pattern)."""


class Handler:
    def handle_upload(self, io_timeout: float):
        self.server.settimeout(io_timeout)
        (conn, _addr) = self.server.accept()
        conn.settimeout(io_timeout)
        header = conn.recv(4)
        return header
