"""Known-good: branches only on public lengths/presence (SF001)."""


def hygienic(seed: bytes, extra=None) -> bytes:
    if len(seed) != 16:
        raise ValueError("incorrect seed size")
    if extra is None:
        return seed
    return seed + extra
