"""RL002: the early return leaks the freshly-dialed socket."""
import socket


def probe(host, port, want):
    sock = socket.create_connection((host, port))
    if not want:
        return None
    sock.close()
    return True
