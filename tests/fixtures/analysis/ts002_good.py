"""Known-good: host casts only touch static shape data (TS002)."""

import jax


def leading(x: jax.Array) -> int:
    return int(x.shape[0])
