"""Pallas fused Keccak-p[1600] kernel: all rounds resident in VMEM.

The XLA path (ops/keccak_jax.keccak_p1600) runs the round loop under
lax.scan — correct and portable, but the 50-array scan carry round-
trips through HBM between rounds unless XLA fuses the unrolled form
(PERF.md §3: the last ~2x to the VPU ceiling).  This kernel keeps the
whole 1600-bit state in VMEM for all 12 rounds: one HBM read of the
state, 12 rounds of pure VPU work, one HBM write.

Layout: lane-major planes (50, B) uint32 — lane half i of A[x+5y] is
row i, the batch rides the 128-wide vector lanes (the same layout the
XLA path uses internally, so adoption is a transpose at the call
boundary, already present there).  B is padded to the 128-lane tile.

Gated by MASTIC_KECCAK_PALLAS=1 (read in ops/keccak_jax at import):
untested on real hardware until the tunnel returns, the interpret-mode
equivalence suite (tests/test_ops_keccak.py) locks bit-exactness
against the scan path on CPU.
"""

import jax
import jax.numpy as jnp
import numpy as np

from ..keccak import ROUND_CONSTANTS

_U32 = jnp.uint32
_LANE = 128   # TPU vector lane width (uint32 tile: 8 x 128)
_BLOCK_B = 512  # max batch elements per grid step (100 KB VMEM)


def _make_kernel(start: int, end: int):
    def kernel(state_ref, out_ref):
        # state: (50, B_block) — rows 0..24 = lo halves, 25..49 = hi.
        # The round math is the scan path's _keccak_round verbatim
        # (pallas refs load as ordinary jax arrays, so the shared
        # definition applies unchanged).  Rows are kept as (1, block)
        # 2-D tiles — Mosaic's vector lowering expects >= 2-D; the
        # round ops are all elementwise, so the leading unit axis
        # broadcasts through unchanged.
        from .keccak_jax import _keccak_round

        a = [(state_ref[i:i + 1, :], state_ref[25 + i:26 + i, :])
             for i in range(25)]
        for r in range(start, end):  # unrolled: state stays in VMEM
            rc = ROUND_CONSTANTS[r]
            a = _keccak_round(a, _U32(rc & 0xFFFFFFFF), _U32(rc >> 32))
        for i in range(25):
            out_ref[i:i + 1, :] = a[i][0]
            out_ref[25 + i:26 + i, :] = a[i][1]

    return kernel


_CALL_CACHE: dict = {}


def _pallas_permute(state: jax.Array, rounds: tuple,
                    interpret: bool, block: int) -> jax.Array:
    """state (50, B) uint32, B a multiple of `block`; `rounds` is the
    half-open [start, end) range into ROUND_CONSTANTS."""
    from jax.experimental import pallas as pl

    B = state.shape[1]
    assert B % block == 0, (B, block)
    key = (rounds, B, block, interpret)
    call = _CALL_CACHE.get(key)
    if call is None:
        # mastic-allow: PL004 — the 50-row block equals the full
        # array dim (25 lo + 25 hi lane halves, never tiled), the
        # case Mosaic accepts for a non-multiple-of-8 sublane dim
        call = pl.pallas_call(
            _make_kernel(*rounds),
            out_shape=jax.ShapeDtypeStruct((50, B), jnp.uint32),
            grid=(B // block,),
            in_specs=[pl.BlockSpec((50, block), lambda i: (0, i))],
            out_specs=pl.BlockSpec((50, block), lambda i: (0, i)),
            interpret=interpret,
        )
        _CALL_CACHE[key] = call
    return call(state)


def keccak_p1600_pallas(lo: jax.Array, hi: jax.Array,
                        num_rounds: int = 12,
                        interpret: bool = False,
                        round_range: tuple = None):
    """Drop-in twin of ops/keccak_jax.keccak_p1600: lo/hi (..., 25)
    uint32 -> permuted (lo, hi).  Batch is flattened, transposed to
    lane-major planes, padded to the 128-lane tile, and run through
    the fused VMEM kernel.

    `round_range` overrides the usual last-`num_rounds` window with an
    explicit [start, end) into ROUND_CONSTANTS — the chained
    equivalence test applies the 12 rounds one kernel at a time, which
    is what pins each round's constant offset without the >1 h
    interpret compile of the fully unrolled kernel."""
    batch_shape = lo.shape[:-1]
    flat = int(np.prod(batch_shape)) if batch_shape else 1
    state = jnp.concatenate([
        lo.reshape(flat, 25).T, hi.reshape(flat, 25).T], axis=0)
    # Pad to a multiple of the block size so the grid covers every
    # column (the block is the largest power-of-2 <= _BLOCK_B that
    # divides the lane-padded batch — no dropped remainder).
    lanes = -(-flat // _LANE) * _LANE
    block = _BLOCK_B
    while lanes % block:
        block //= 2
    pad = lanes - flat
    if pad:
        state = jnp.pad(state, ((0, 0), (0, pad)))
    rounds = (round_range if round_range is not None
              else (24 - num_rounds, 24))
    out = _pallas_permute(state, rounds, interpret, block)
    out = out[:, :flat]
    return (out[:25].T.reshape(batch_shape + (25,)),
            out[25:].T.reshape(batch_shape + (25,)))
