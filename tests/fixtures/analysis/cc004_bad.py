"""CC004 bad fixture: sleeping while holding the lock."""
import threading
import time


class Pacer:
    def __init__(self):
        self.lock = threading.Lock()
        self.ticks = 0

    def tick(self):
        with self.lock:
            self.ticks = self.ticks + 1
            time.sleep(0.1)
