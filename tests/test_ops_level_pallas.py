"""Differential tests: the fused level-step megakernel
(ops/level_pallas.py) vs the scan-path eval_step.

The chained form runs ONE pallas kernel per pipeline stage (39
stages: extend sigma, 11 extend-AES stages, correct, 11 convert-AES
stages, convert finish, absorb, 12 Keccak rounds, squeeze), so a
passing run pins every AES round key, every Keccak round constant and
the final AES round's missing MixColumns individually — the r5
technique that avoids the fused form's >1 h interpret compile."""

import numpy as np
import pytest

pytest.importorskip("jax.experimental.pallas")

import jax.numpy as jnp

from mastic_tpu.backend.vidpf_jax import BatchedVidpf, EvalState
from mastic_tpu.backend.xof_jax import ts_prefix
from mastic_tpu.dst import USAGE_NODE_PROOF, dst
from mastic_tpu.field import Field64, Field128

CTX = b"level kernel test"
KEY_SIZE = 16


def _level_inputs(vid, num_reports, num_parents, seed=1):
    """Random parent state + correction-word slice + per-level binder
    for one eval_step at (num_reports x num_parents)."""
    rng = np.random.default_rng(seed)
    n = vid.spec.num_limbs
    vl = vid.VALUE_LEN
    nonces = jnp.asarray(rng.integers(0, 256, (num_reports, 16),
                                      np.uint8))
    (ext_rk, conv_rk) = vid.roundkeys(CTX, nonces)
    parents = EvalState(
        seed=jnp.asarray(rng.integers(
            0, 256, (num_reports, num_parents, 16), np.uint8)),
        ctrl=jnp.asarray(rng.integers(
            0, 2, (num_reports, num_parents)).astype(bool)),
        w=jnp.zeros((num_reports, num_parents, vl, n), jnp.uint32),
        proof=jnp.zeros((num_reports, num_parents, 32), jnp.uint8))
    cw = (jnp.asarray(rng.integers(0, 256, (num_reports, 16),
                                   np.uint8)),
          jnp.asarray(rng.integers(0, 2, (num_reports, 2))
                      .astype(bool)),
          jnp.asarray(rng.integers(0, 1 << 16, (num_reports, vl, n),
                                   dtype=np.uint32)),
          jnp.asarray(rng.integers(0, 256, (num_reports, 32),
                                   np.uint8)))
    binder = rng.integers(
        0, 256, (2 * num_parents, 4 + (vid.BITS + 7) // 8), np.uint8)
    return (ext_rk, conv_rk, parents, cw, binder)


def _assert_matches_eval_step(vid, num_reports, num_parents, seed=1):
    from mastic_tpu.ops.level_pallas import level_step_pallas

    (ext_rk, conv_rk, parents, cw, binder) = _level_inputs(
        vid, num_reports, num_parents, seed)
    (child, ok) = vid.eval_step(ext_rk, conv_rk, parents, cw, CTX,
                                binder)
    prefix = ts_prefix(dst(CTX, USAGE_NODE_PROOF), KEY_SIZE)
    (seed_b, ct, w, ok_k, proof) = level_step_pallas(
        vid.spec, vid.convert_blocks, ext_rk, conv_rk, parents.seed,
        parents.ctrl, cw, prefix, binder, interpret=True)
    np.testing.assert_array_equal(np.asarray(seed_b),
                                  np.asarray(child.seed))
    np.testing.assert_array_equal(np.asarray(ct),
                                  np.asarray(child.ctrl))
    np.testing.assert_array_equal(np.asarray(w), np.asarray(child.w))
    np.testing.assert_array_equal(np.asarray(proof),
                                  np.asarray(child.proof))
    np.testing.assert_array_equal(
        np.asarray(jnp.all(ok_k, axis=-1)), np.asarray(ok))


def test_level_pallas_matches_eval_step():
    """Field64 (MasticCount shape, convert_blocks=2) at a small tile:
    all 39 chained stages bit-exact vs the scan path."""
    _assert_matches_eval_step(BatchedVidpf(Field64, 16, 2), 64, 4)


@pytest.mark.slow
@pytest.mark.parametrize("field,vl,reports,parents,bits", [
    (Field64, 2, 40, 2, 256),   # frontier < 8, reports % 32 != 0
    (Field64, 2, 33, 1, 8),     # single parent, odd report count
    (Field128, 2, 64, 3, 64),   # convert_blocks=3 -> 4-parent blocks
    (Field128, 1, 32, 5, 32),   # Field128 narrow payload
], ids=["f64-edge", "f64-single", "f128-m3", "f128-vl1"])
def test_level_pallas_edge_shapes(field, vl, reports, parents, bits):
    """Padding paths: report lanes below/off the 32-packing, parent
    counts below/off the grid block, Field64 vs Field128 payload
    widths and an odd convert-block count."""
    _assert_matches_eval_step(BatchedVidpf(field, bits, vl),
                              reports, parents, seed=2)


@pytest.mark.slow
def test_level_pallas_headline_tile():
    """The headline steady-state tile (4096 reports x 64 frontier,
    256-bit tree): the shape the chip session measures, bit-exact in
    interpret mode on the CPU fabric (acceptance criterion)."""
    _assert_matches_eval_step(BatchedVidpf(Field64, 256, 2), 4096, 64,
                              seed=3)


def test_eval_step_gates_to_megakernel(monkeypatch):
    """The MASTIC_LEVEL_PALLAS backend path: eval_step routes
    supported shapes through the megakernel (same outputs as the
    scan path) and keeps the scan path for unsupported ones."""
    from mastic_tpu.backend import vidpf_jax
    from mastic_tpu.ops import level_pallas

    vid = BatchedVidpf(Field64, 16, 2)
    (ext_rk, conv_rk, parents, cw, binder) = _level_inputs(vid, 32, 2)
    (want_child, want_ok) = vid.eval_step(ext_rk, conv_rk, parents,
                                          cw, CTX, binder)

    calls = []
    orig = level_pallas.level_step_pallas

    def spy(*args, **kwargs):
        calls.append(1)
        return orig(*args, **kwargs)

    monkeypatch.setattr(level_pallas, "level_step_pallas", spy)
    monkeypatch.setattr(vidpf_jax, "USE_LEVEL_PALLAS", True)
    (child, ok) = vid.eval_step(ext_rk, conv_rk, parents, cw, CTX,
                                binder)
    assert calls, "supported shape must take the megakernel path"
    for (got, want) in zip(child, want_child):
        np.testing.assert_array_equal(np.asarray(got),
                                      np.asarray(want))
    np.testing.assert_array_equal(np.asarray(ok), np.asarray(want_ok))

    # Tiny batches (< 32 reports) and huge-payload converts fall back
    # to the scan path rather than fail.
    calls.clear()
    (ext_rk2, conv_rk2, parents2, cw2, binder2) = _level_inputs(
        vid, 8, 2)
    (child2, ok2) = vid.eval_step(ext_rk2, conv_rk2, parents2, cw2,
                                  CTX, binder2)
    assert not calls, "tiny batch must keep the scan path"
    assert child2.seed.shape == (8, 4, 16)

    big = BatchedVidpf(Field128, 16, 40)   # convert_blocks > 8
    assert not level_pallas.supports(big.convert_blocks, 20, 6)
    (ext_rk3, conv_rk3, parents3, cw3, binder3) = _level_inputs(
        big, 32, 2)
    (child3, _ok3) = big.eval_step(ext_rk3, conv_rk3, parents3, cw3,
                                   CTX, binder3)
    assert not calls, "huge-payload convert must keep the scan path"
    assert child3.seed.shape == (32, 4, 16)
