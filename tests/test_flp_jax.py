"""Differential tests: batched FLP (flp/flp_jax.py) vs the scalar
byte-exact layer (flp/flp.py), for all five circuits.

The scalar layer is conformance-locked against the reference vectors,
so element-exact agreement here extends that lock to the device path.
"""

import random

import jax
import numpy as np
import pytest

pytestmark = pytest.mark.slow

from mastic_tpu.field import Field64, Field128
from mastic_tpu.flp.circuits import (Count, Histogram, MultihotCountVec,
                                     Sum, SumVec)
from mastic_tpu.flp.flp import FlpBBCGGI19
from mastic_tpu.flp.flp_jax import BatchedFlp
from mastic_tpu.ops.field_jax import spec_for

CIRCUITS = [
    ("count", lambda: Count(Field64), 1),
    ("sum", lambda: Sum(Field64, 7), 5),
    ("sumvec", lambda: SumVec(Field128, 3, 1, 1), [1, 0, 1]),
    ("histogram", lambda: Histogram(Field128, 4, 2), 2),
    ("multihot", lambda: MultihotCountVec(Field128, 4, 2, 2),
     [True, False, True, False]),
]


def _rand_vec(field, length, rng):
    return [field(rng.randrange(field.MODULUS)) for _ in range(length)]


def _to_limbs(spec, vec):
    return np.stack([spec.int_to_limbs(x.int()) for x in vec]) \
        if vec else np.zeros((0, spec.num_limbs), np.uint32)


def _from_limbs(spec, field, arr):
    return [field(spec.limbs_to_int(arr[i])) for i in range(arr.shape[0])]


@pytest.mark.parametrize("name,make,meas", CIRCUITS,
                         ids=[c[0] for c in CIRCUITS])
def test_query_matches_scalar(name, make, meas):
    rng = random.Random(f"query {name}")
    flp = FlpBBCGGI19(make())
    bf = BatchedFlp(flp)
    spec = spec_for(flp.field)
    field = flp.field

    batch_meas = []
    batch_proof = []
    batch_qr = []
    batch_jr = []
    expected = []
    for _ in range(5):
        # Random (not necessarily valid) algebra inputs: query is
        # deterministic in them, so exact agreement is well-defined.
        meas_share = _rand_vec(field, flp.MEAS_LEN, rng)
        proof_share = _rand_vec(field, flp.PROOF_LEN, rng)
        query_rand = _rand_vec(field, flp.QUERY_RAND_LEN, rng)
        joint_rand = _rand_vec(field, flp.JOINT_RAND_LEN, rng)
        expected.append(flp.query(meas_share, proof_share, query_rand,
                                  joint_rand, 2))
        batch_meas.append(_to_limbs(spec, meas_share))
        batch_proof.append(_to_limbs(spec, proof_share))
        batch_qr.append(_to_limbs(spec, query_rand))
        batch_jr.append(_to_limbs(spec, joint_rand))

    if flp.JOINT_RAND_LEN:
        fn = jax.jit(lambda m, p, q, j: bf.query(m, p, q, j, 2))
        (verifier, ok) = fn(np.stack(batch_meas), np.stack(batch_proof),
                            np.stack(batch_qr), np.stack(batch_jr))
    else:
        fn = jax.jit(lambda m, p, q: bf.query(m, p, q, None, 2))
        (verifier, ok) = fn(np.stack(batch_meas), np.stack(batch_proof),
                            np.stack(batch_qr))
    verifier = np.asarray(verifier)
    assert bool(np.all(np.asarray(ok)))
    for (r, exp) in enumerate(expected):
        got = _from_limbs(spec, field, verifier[r])
        assert got == exp, f"report {r}"


@pytest.mark.parametrize("name,make,meas", CIRCUITS,
                         ids=[c[0] for c in CIRCUITS])
def test_prove_matches_scalar(name, make, meas):
    rng = random.Random(f"prove {name}")
    flp = FlpBBCGGI19(make())
    bf = BatchedFlp(flp)
    spec = spec_for(flp.field)
    field = flp.field

    encoded = flp.encode(meas)
    batch_meas = []
    batch_pr = []
    batch_jr = []
    expected = []
    for _ in range(4):
        prove_rand = _rand_vec(field, flp.PROVE_RAND_LEN, rng)
        joint_rand = _rand_vec(field, flp.JOINT_RAND_LEN, rng)
        expected.append(flp.prove(encoded, prove_rand, joint_rand))
        batch_meas.append(_to_limbs(spec, encoded))
        batch_pr.append(_to_limbs(spec, prove_rand))
        batch_jr.append(_to_limbs(spec, joint_rand))

    if flp.JOINT_RAND_LEN:
        fn = jax.jit(bf.prove)
        proof = np.asarray(fn(np.stack(batch_meas), np.stack(batch_pr),
                              np.stack(batch_jr)))
    else:
        fn = jax.jit(lambda m, p: bf.prove(m, p, None))
        proof = np.asarray(fn(np.stack(batch_meas), np.stack(batch_pr)))
    for (r, exp) in enumerate(expected):
        got = _from_limbs(spec, field, proof[r])
        assert got == exp, f"report {r}"


@pytest.mark.parametrize("name,make,meas", CIRCUITS,
                         ids=[c[0] for c in CIRCUITS])
def test_roundtrip_decide(name, make, meas):
    """Honest prove -> split shares -> query x2 -> sum -> decide=True;
    a tampered measurement share flips decide to False."""
    rng = random.Random(f"decide {name}")
    flp = FlpBBCGGI19(make())
    bf = BatchedFlp(flp)
    spec = spec_for(flp.field)
    field = flp.field

    encoded = flp.encode(meas)
    prove_rand = _rand_vec(field, flp.PROVE_RAND_LEN, rng)
    joint_rand = _rand_vec(field, flp.JOINT_RAND_LEN, rng)
    proof = flp.prove(encoded, prove_rand, joint_rand)
    query_rand = _rand_vec(field, flp.QUERY_RAND_LEN, rng)

    meas0 = _rand_vec(field, flp.MEAS_LEN, rng)
    meas1 = [a - b for (a, b) in zip(encoded, meas0)]
    proof0 = _rand_vec(field, flp.PROOF_LEN, rng)
    proof1 = [a - b for (a, b) in zip(proof, proof0)]

    if flp.JOINT_RAND_LEN:
        qfn = jax.jit(lambda m, p, q, j: bf.query(m, p, q, j, 2))
    else:
        qfn = jax.jit(lambda m, p, q, j: bf.query(m, p, q, None, 2))
    verifiers = []
    for (mshare, pshare) in ((meas0, proof0), (meas1, proof1)):
        (verifier, ok) = qfn(
            _to_limbs(spec, mshare)[None], _to_limbs(spec, pshare)[None],
            _to_limbs(spec, query_rand)[None],
            _to_limbs(spec, joint_rand)[None])
        assert bool(np.asarray(ok)[0])
        verifiers.append(_from_limbs(spec, field,
                                     np.asarray(verifier)[0]))

    summed = [a + b for (a, b) in zip(*verifiers)]
    assert flp.decide(summed)
    dfn = jax.jit(bf.decide)
    got = dfn(_to_limbs(spec, summed)[None])
    assert bool(np.asarray(got)[0])

    # Tamper: shift one element of one verifier share (models a bad
    # measurement); both scalar and batched must reject.
    bad = list(summed)
    bad[0] += field(1)
    assert not flp.decide(bad)
    got_bad = dfn(_to_limbs(spec, bad)[None])
    assert not bool(np.asarray(got_bad)[0])
