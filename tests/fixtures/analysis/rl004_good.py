"""RL004 clean: None-out after close makes a repeat call
impossible (and the rebinding retires the tracked fact)."""
import socket


def shutdown(host, port):
    sock = socket.create_connection((host, port))
    sock.close()
    sock = None
    return sock
