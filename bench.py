"""Benchmark: steady-state VIDPF evaluation throughput on one chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The metric is the BASELINE.json north star — VIDPF node evaluations
per second per chip at 256-bit tree depth, where one node evaluation
is the full extend + correct + convert + node-proof pipeline of
/root/reference/poc/vidpf.py:281-325 (2 fixed-key-AES blocks + 2 AES
convert blocks + 1 TurboSHAKE-128 hash per node, reference op model in
BASELINE.md).  The reference publishes no timing numbers, so
vs_baseline compares against this repo's own scalar CPU reference
layer (the same byte-exact math the reference's Python PoC runs),
measured in-process.

Shapes mimic the heavy-hitters steady state: a pruned frontier of
constant width marching down a 256-level tree; each timed step is one
tree level over (reports x frontier) with a traced node binder so a
single compiled program serves every level.
"""

import argparse
import json
import os
import sys
import threading
import time


def _watchdog(seconds: float):
    """Emit a failure JSON line and hard-exit if the chip never comes
    up (the remote-TPU tunnel can block indefinitely)."""

    def fire():
        print(json.dumps({
            "metric": "vidpf_node_evals_per_sec_per_chip_256bit",
            "value": 0.0, "unit": "evals/s",
            "vs_baseline": 0.0, "error": "watchdog timeout",
        }), flush=True)
        os._exit(2)

    timer = threading.Timer(seconds, fire)
    timer.daemon = True
    timer.start()
    return timer


def scalar_rate(bits: int = 256, level: int = 3) -> float:
    """Node evals/sec of the scalar byte-exact reference layer."""
    from mastic_tpu.field import Field64
    from mastic_tpu.vidpf import Vidpf

    vidpf = Vidpf(Field64, bits, 2)
    alpha = tuple(bool(i % 2) for i in range(bits))
    beta = [Field64(1), Field64(1)]
    nonce = bytes(16)
    rand = bytes(range(32))
    (cws, keys) = vidpf.gen(alpha, beta, b"bench", nonce, rand)
    prefixes = tuple(
        tuple(bool((v >> (level - i)) & 1) for i in range(level + 1))
        for v in range(2 ** (level + 1)))
    t0 = time.perf_counter()
    (_, tree) = vidpf.eval_level_synchronous(
        0, cws, keys[0], level, prefixes, b"bench", nonce)
    dt = time.perf_counter() - t0
    nodes = sum(len(lvl) for lvl in tree.levels)
    return nodes / dt


def batched_rate(reports: int, frontier: int, steps: int,
                 bits: int = 256) -> float:
    """Steady-state node evals/sec of the batched backend on the
    default chip."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    from mastic_tpu import MasticCount
    from mastic_tpu.backend.mastic_jax import BatchedMastic
    from mastic_tpu.backend.vidpf_jax import EvalState

    bm = BatchedMastic(MasticCount(bits))
    vid = bm.vidpf
    ctx = b"bench"
    rng = np.random.default_rng(0)

    nonces = jnp.asarray(rng.integers(0, 256, (reports, 16),
                                      dtype=np.uint8))
    roundkeys = jax.jit(lambda n: vid.roundkeys(ctx, n))
    (ext_rk, conv_rk) = roundkeys(nonces)

    # One level's inputs; binder is traced so one compile serves all
    # levels (at depth >= 248 the path encoding is 32 bytes).
    def mk_state(num_nodes):
        return EvalState(
            seed=jnp.asarray(rng.integers(
                0, 256, (reports, num_nodes, 16), dtype=np.uint8)),
            ctrl=jnp.asarray(rng.integers(
                0, 2, (reports, num_nodes)).astype(bool)),
            w=jnp.zeros((reports, num_nodes, 2, 4), jnp.uint32),
            proof=jnp.zeros((reports, num_nodes, 32), jnp.uint8),
        )

    cw = (
        jnp.asarray(rng.integers(0, 256, (reports, 16), np.uint8)),
        jnp.asarray(rng.integers(0, 2, (reports, 2)).astype(bool)),
        jnp.asarray(rng.integers(0, 1 << 16, (reports, 2, 4),
                                 dtype=np.uint32)),
        jnp.asarray(rng.integers(0, 256, (reports, 32), np.uint8)),
    )
    binder = jnp.asarray(rng.integers(0, 256, (2 * frontier, 36),
                                      dtype=np.uint8))
    keep = np.arange(0, 2 * frontier, 2)

    @jax.jit
    def step(seed, ctrl, binder):
        parents = EvalState(seed=seed, ctrl=ctrl,
                            w=jnp.zeros_like(state.w),
                            proof=jnp.zeros_like(state.proof))
        (child, ok) = vid.eval_step(ext_rk, conv_rk, parents, cw, ctx,
                                    binder)
        # Prune back to the frontier width (threshold survivors).
        return (child.seed[:, keep], child.ctrl[:, keep],
                child.proof, ok)

    state = mk_state(frontier)
    (seed, ctrl) = (state.seed, state.ctrl)
    # Warmup / compile.
    (seed, ctrl, _, _) = step(seed, ctrl, binder)
    jax.block_until_ready(seed)

    t0 = time.perf_counter()
    for _ in range(steps):
        (seed, ctrl, proof, ok) = step(seed, ctrl, binder)
    jax.block_until_ready(seed)
    dt = time.perf_counter() - t0
    return reports * 2 * frontier * steps / dt


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--reports", type=int, default=4096)
    parser.add_argument("--frontier", type=int, default=64)
    parser.add_argument("--steps", type=int, default=16)
    parser.add_argument("--cpu", action="store_true",
                        help="force the CPU backend (local sanity)")
    parser.add_argument("--watchdog", type=float, default=900.0)
    args = parser.parse_args()

    timer = _watchdog(args.watchdog)
    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    requested = os.environ.get("JAX_PLATFORMS", "").strip()
    if requested and "axon" not in requested.split(","):
        jax.config.update("jax_platforms", requested)

    base = scalar_rate()
    rate = batched_rate(args.reports, args.frontier, args.steps)
    timer.cancel()
    print(json.dumps({
        "metric": "vidpf_node_evals_per_sec_per_chip_256bit",
        "value": round(rate, 1),
        "unit": "evals/s",
        "vs_baseline": round(rate / base, 1),
    }), flush=True)


if __name__ == "__main__":
    main()
