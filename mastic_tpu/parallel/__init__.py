"""Multi-chip scaling: device meshes, sharded prep, collective
aggregation.

The reference is a single Python process (SURVEY.md §2.3); the two
protocol axes that parallelize are reports (data parallel) and
prefix-tree nodes (the within-level grid).  Here both are mesh axes:
reports shard across chips like a batch, nodes like a sequence, and
share aggregation is an XLA all-reduce over the report axis riding
ICI.  Inter-*party* traffic (leader <-> helper) stays on the host/DCN
boundary carrying the byte-exact wire messages (mastic_tpu.mastic).
"""

from .mesh import (install_grid_sharding, make_mesh, place_replicated,
                   place_reports, shard_batch,
                   shard_incremental_runner, sharded_gen_fn,
                   sharded_prep_fn, sharded_round_fn)

__all__ = ["install_grid_sharding", "make_mesh", "place_replicated",
           "place_reports", "shard_batch", "shard_incremental_runner",
           "sharded_gen_fn", "sharded_prep_fn", "sharded_round_fn"]
