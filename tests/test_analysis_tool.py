"""Fixture self-tests for every tools/analysis rule (fast tier).

Each analyzer rule has at least one known-bad fixture it must flag and
one known-good fixture it must pass (tests/fixtures/analysis/), plus
the suppression mechanics (justified allow silences, bare allow and
stale allow are findings) and the shipped-tree gate (`make analyze`
must exit 0 on the repo as committed).
"""

import pathlib

import pytest

from tools import analysis

FIXTURES = pathlib.Path(__file__).parent / "fixtures" / "analysis"

# rule -> (bad fixture, good fixture, pass name)
CASES = {
    "TS001": ("ts001_bad.py", "ts001_good.py", "tracesafe"),
    "TS002": ("ts002_bad.py", "ts002_good.py", "tracesafe"),
    "TS003": ("ts003_bad.py", "ts003_good.py", "tracesafe"),
    "TS004": ("ts004_bad.py", "ts004_good.py", "tracesafe"),
    "DT001": ("dt001_bad.py", "dt001_good.py", "dtypes"),
    "DT002": ("dt002_bad.py", "dt002_good.py", "dtypes"),
    "DT003": ("dt003_bad.py", "dt003_good.py", "dtypes"),
    "SF001": ("sf001_bad.py", "sf001_good.py", "secretflow"),
    "SF002": ("sf002_bad.py", "sf002_good.py", "secretflow"),
    "PL001": ("pl001_bad.py", "pl001_good.py", "pallasck"),
    "PL002": ("pl002_bad.py", "pl002_good.py", "pallasck"),
    "PL003": ("pl003_bad.py", "pl003_good.py", "pallasck"),
    "PL004": ("pl004_bad.py", "pl004_good.py", "pallasck"),
    "RB001": ("rb001_bad.py", "rb001_good.py", "robustness"),
    "RB002": ("rb002_bad.py", "rb002_good.py", "robustness"),
    "RB003": ("rb003_bad.py", "rb003_good.py", "robustness"),
    "RB004": ("rb004_bad.py", "rb004_good.py", "robustness"),
    "RB005": ("rb005_bad.py", "rb005_good.py", "robustness"),
    "OB001": ("ob001_bad.py", "ob001_good.py", "observability"),
}


def run_fixture(name: str, pass_name: str):
    return analysis.analyze_paths([FIXTURES / name],
                                  only_passes={pass_name},
                                  force_scope=True)


@pytest.mark.parametrize("rule", sorted(CASES))
def test_bad_fixture_is_flagged(rule):
    (bad, _good, pass_name) = CASES[rule]
    (findings, _suppressed) = run_fixture(bad, pass_name)
    rules_hit = {f.rule for f in findings}
    assert rule in rules_hit, (
        f"{bad} must trigger {rule}; got {[f.text() for f in findings]}")


@pytest.mark.parametrize("rule", sorted(CASES))
def test_good_fixture_is_clean(rule):
    (_bad, good, pass_name) = CASES[rule]
    (findings, suppressed) = run_fixture(good, pass_name)
    assert findings == [] and suppressed == [], (
        f"{good} must be clean; got {[f.text() for f in findings]}")


def test_every_rule_has_a_fixture_case():
    declared = set()
    for mod in analysis.PASSES:
        declared |= set(mod.RULES)
    assert declared == set(CASES), (
        "every analyzer rule needs a bad+good fixture pair here")


# -- suppression mechanics -------------------------------------------

def test_justified_suppression_silences_finding():
    (findings, suppressed) = run_fixture("al_good.py", "secretflow")
    assert findings == []
    assert [f.rule for f in suppressed] == ["SF001"]


def test_suppression_covers_multiline_statement():
    (findings, suppressed) = run_fixture("al_multiline_good.py",
                                         "secretflow")
    assert findings == []
    assert {f.rule for f in suppressed} == {"SF002"}
    assert len(suppressed) == 2    # both lines of the statement


def test_bare_suppression_is_flagged():
    (findings, _suppressed) = run_fixture("al001_bad.py", "secretflow")
    assert [f.rule for f in findings] == ["AL001"]


def test_stale_suppression_is_flagged():
    (findings, _suppressed) = run_fixture("al002_bad.py", "secretflow")
    assert [f.rule for f in findings] == ["AL002"]


def test_syntax_error_is_a_finding():
    (findings, _suppressed) = run_fixture("xx000_bad.py", "tracesafe")
    assert [f.rule for f in findings] == ["XX000"]


# -- the gate itself -------------------------------------------------

def test_shipped_tree_is_clean():
    """`make analyze` must exit 0 on the repo as committed: every real
    finding is fixed or carries a justified inline mastic-allow."""
    (findings, suppressed) = analysis.analyze_paths(
        analysis.default_files())
    assert findings == [], [f.text() for f in findings]
    # The suppressed set is the documented-risk register; it must be
    # non-empty (the passes do fire on real code) and every entry
    # carries a justification (AL001 would have failed above).
    assert len(suppressed) >= 4
    classes = {f.rule[:2] for f in suppressed}
    assert {"TS", "DT", "SF", "PL"} <= classes, (
        "each pass class must have at least one documented real "
        f"finding; got {classes}")


def test_cli_json_output():
    import json
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, "-m", "tools.analysis", "--json",
         str(FIXTURES / "sf001_bad.py"), "--pass", "secretflow",
         "--force-scope"],
        capture_output=True, text=True,
        cwd=str(pathlib.Path(__file__).parent.parent))
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)
    assert payload["findings"][0]["rule"] == "SF001"
