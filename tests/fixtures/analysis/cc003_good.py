"""CC003 good fixture: readers get a snapshot copy."""
import threading


class Board:
    def __init__(self):
        self.lock = threading.Lock()
        self.table = {}

    def put(self, k, v):
        with self.lock:
            self.table[k] = v

    def view(self):
        with self.lock:
            return dict(self.table)
