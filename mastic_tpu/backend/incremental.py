"""Cross-round incremental VIDPF evaluation for heavy hitters.

The reference notes that an aggregator can cache the prefix tree
across collection rounds (/root/reference/poc/vidpf.py:243-245); the
batched backend makes that the core execution model:

* each aggregator carries, per tree depth, the payload + node-proof
  arrays of every node materialized so far (pruned each round to the
  ancestors of the live candidate-prefix set), plus the full
  seed/ctrl state of the newest depth;
* a round at level L gathers the surviving depth-(L-1) parents and
  runs ONE batched eval_step — O(frontier) node evaluations per round
  instead of re-walking the whole tree from the root (O(level *
  frontier));
* the eval-proof binders (payload / onehot checks, reference
  mastic.py:258-287) still cover the full ancestor tree byte-exactly:
  they are assembled from the carried arrays with host-computed
  permutations and hashed with a runtime-length sponge.

Shapes are *capacity-static*: every per-depth array is padded to a
fixed node width W and the depth axis to BITS, with live counts /
gather indices / binder lengths passed as runtime inputs.  One
compiled program therefore serves every level of a heavy-hitters run
(the ragged-frontier strategy of SURVEY.md §7 hard part 5).

The host side (RoundPlan) mirrors backend/schedule.py but emits
runtime index tensors instead of baked constants.
"""

from typing import NamedTuple, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..common import to_le_bytes
from ..dst import (USAGE_EVAL_PROOF, USAGE_NODE_PROOF,
                   USAGE_ONEHOT_CHECK, USAGE_PAYLOAD_CHECK, dst, dst_alg)
from ..ops.keccak_jax import turbo_shake128_dynamic
from ..vidpf import PROOF_SIZE, Path, encode_path
from .mastic_jax import BatchedMastic
from .vidpf_jax import KEY_SIZE, EvalState
from .xof_jax import ts_prefix, turboshake_xof

_U8 = jnp.uint8


class Carry(NamedTuple):
    """One aggregator's cross-round state.

    w      (R, BITS, W, VALUE_LEN, n) plain limbs, rows 0..level live
    proof  (R, BITS, W, 32) uint8
    seed   (R, W, 16) uint8 — newest depth only (the PRG frontier)
    ctrl   (R, W) bool
    """
    w: jax.Array
    proof: jax.Array
    seed: jax.Array
    ctrl: jax.Array


def _ancestors(prefixes: Sequence[Path],
               level: int) -> list[list[Path]]:
    """Per depth d <= level, the distinct ancestors of `prefixes` at
    depth d, in lexicographic order."""
    return [
        sorted(set(p[:d + 1] for p in prefixes))
        for d in range(level + 1)
    ]


def needed_paths(prefixes: Sequence[Path], level: int,
                 anc: Optional[list[list[Path]]] = None,
                 ) -> list[list[Path]]:
    """Per depth d <= level, the nodes a round at `level` touches:
    both children of every ancestor of `prefixes` at depth d-1, in
    lexicographic order — exactly the reference's BFS materialization
    order (mastic.py:258-287).  Depends only on (prefixes, level), so
    a restored checkpoint can rebuild the carried-path maps from the
    last aggregation parameter alone."""
    if anc is None:
        anc = _ancestors(prefixes, level)
    return [
        [par + (b,) for par in (anc[d - 1] if d else [()])
         for b in (False, True)]
        for d in range(level + 1)
    ]


def _next_pow2(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


class RoundPlan:
    """Host-side runtime inputs for one incremental round.

    Carried depths use *creation-time layouts*: the node set at depth
    d only ever shrinks across rounds (prefixes descend from
    survivors), so rows written when depth d was the new level stay
    valid forever and no per-round compaction gather is needed — the
    binder assembly gathers exactly the live rows by position.
    `layouts[d]` is depth d's creation layout (list of paths, one per
    node slot); the plan appends `layout_new` for the new level.

    The eval-proof binders still cover the reference's BFS
    materialization order byte-exactly (mastic.py:258-287): per depth,
    the CURRENT needed set in lexicographic order, located inside the
    creation layouts.  Gather index arrays are bucketed to the next
    power of two of the live row count, so one compiled program serves
    a range of levels and the hashed/gathered data scales with the
    live tree, not the bits x width capacity.
    """

    def __init__(self, prefixes: Sequence[Path], level: int, bits: int,
                 width: int, layouts: list):
        if any(len(p) != level + 1 for p in prefixes):
            raise ValueError("prefix with incorrect length")
        if len(set(prefixes)) != len(prefixes):
            raise ValueError("candidate prefixes are non-unique")
        if level != len(layouts):
            raise ValueError(
                f"incremental rounds must advance one level at a time "
                f"(have layouts for depths 0..{len(layouts) - 1}, "
                f"round is at level {level})")
        half = width // 2
        self.level = level
        self.width = width
        self.prefixes = tuple(prefixes)

        anc = _ancestors(prefixes, level)
        # Ancestor depths (< level) each spawn both children inside a
        # width-W row, so they must fit W/2; the frontier itself lives
        # inside layout_new (2*|anc[level-1]| <= W by the same check),
        # so a full-width frontier is fine.
        if any(len(anc[d]) > half for d in range(level)):
            raise ValueError("frontier exceeds padded width")
        # The new level's layout: both children of every ancestor at
        # level-1, lexicographic (== needed_paths(...)[level]).
        if level == 0:
            self.layout_new = [(False,), (True,)]
        else:
            self.layout_new = [p + (b,) for p in anc[level - 1]
                               for b in (False, True)]
        full = list(layouts) + [self.layout_new]
        pos_maps = [{p: i for (i, p) in enumerate(lay)} for lay in full]

        # Parents of the new level inside the previous frontier state
        # (depth level-1's creation layout).
        self.parent_idx = np.zeros(half, np.int32)
        if level == 0:
            self.parent_count = 1
        else:
            for (i, p) in enumerate(anc[level - 1]):
                self.parent_idx[i] = pos_maps[level - 1][p]
            self.parent_count = len(anc[level - 1])

        # Node-proof binder bytes for the new children (runtime data;
        # one row per child, same for every report).
        path_bytes = (bits + 7) // 8
        self.binder_capacity = 4 + path_bytes
        self.node_binder = np.zeros((width, self.binder_capacity),
                                    np.uint8)
        head = to_le_bytes(bits, 2) + to_le_bytes(level, 2)
        for (i, p) in enumerate(self.layout_new):
            row = head + encode_path(p)
            self.node_binder[i, :len(row)] = np.frombuffer(row, np.uint8)
        self.binder_len = 4 + (level + 1 + 7) // 8

        # Onehot rows: the current needed set per depth (children of
        # the current ancestors), BFS/lex order, as flattened
        # (depth * width + slot) positions into the carried proofs.
        rows = []
        for d in range(level + 1):
            if d == level:
                current = self.layout_new
            elif d == 0:
                current = [(False,), (True,)]
            else:
                current = [p + (b,) for p in anc[d - 1]
                           for b in (False, True)]
            rows += [d * width + pos_maps[d][p] for p in current]
        self.onehot_rows = len(rows)
        cap_o = _next_pow2(max(1, self.onehot_rows))
        self.onehot_idx = np.zeros(cap_o, np.int32)
        self.onehot_idx[:len(rows)] = rows

        # Payload rows: parent minus its two children, per internal
        # node (the current ancestors at depths < level), each row a
        # triple of flattened positions.
        (par, left, right) = ([], [], [])
        for d in range(level):
            for p in anc[d]:
                par.append(d * width + pos_maps[d][p])
                left.append((d + 1) * width + pos_maps[d + 1][p + (False,)])
                right.append((d + 1) * width + pos_maps[d + 1][p + (True,)])
        self.payload_rows = len(par)
        cap_p = _next_pow2(max(1, self.payload_rows))
        self.payload_parent = np.zeros(cap_p, np.int32)
        self.payload_left = np.zeros(cap_p, np.int32)
        self.payload_right = np.zeros(cap_p, np.int32)
        self.payload_parent[:len(par)] = par
        self.payload_left[:len(left)] = left
        self.payload_right[:len(right)] = right

        # Output gather: position of each prefix in the new layout,
        # bucketed to the next power of two of the live frontier (the
        # same treatment as the binder gathers): the out-share tensor
        # and the masked aggregate scale with the frontier, not the
        # padded width.
        self.num_out = len(self.prefixes)
        cap_out = _next_pow2(max(1, self.num_out))
        self.out_idx = np.zeros(cap_out, np.int32)
        for (i, p) in enumerate(self.prefixes):
            self.out_idx[i] = pos_maps[level][p]


class IncrementalRound(NamedTuple):
    """Traced inputs derived from a RoundPlan.  The gather index
    arrays are capacity-bucketed (power of two >= live rows), so jit
    specializes per bucket — O(log(bits * width)) programs over a full
    heavy-hitters run instead of one per level."""
    level: jax.Array           # () int32
    parent_idx: jax.Array      # (W/2,)
    parent_count: jax.Array    # () int32
    node_binder: jax.Array     # (W, B)
    binder_len: jax.Array      # () int32
    onehot_idx: jax.Array      # (capO,) flattened (depth*W + slot)
    onehot_rows: jax.Array     # () int32
    payload_parent: jax.Array  # (capP,)
    payload_left: jax.Array    # (capP,)
    payload_right: jax.Array   # (capP,)
    payload_rows: jax.Array    # () int32
    out_idx: jax.Array         # (capOut,)


def round_inputs(plan: RoundPlan) -> IncrementalRound:
    return IncrementalRound(
        level=jnp.int32(plan.level),
        parent_idx=jnp.asarray(plan.parent_idx),
        parent_count=jnp.int32(plan.parent_count),
        node_binder=jnp.asarray(plan.node_binder),
        binder_len=jnp.int32(plan.binder_len),
        onehot_idx=jnp.asarray(plan.onehot_idx),
        onehot_rows=jnp.int32(plan.onehot_rows),
        payload_parent=jnp.asarray(plan.payload_parent),
        payload_left=jnp.asarray(plan.payload_left),
        payload_right=jnp.asarray(plan.payload_right),
        payload_rows=jnp.int32(plan.payload_rows),
        out_idx=jnp.asarray(plan.out_idx),
    )


class IncrementalMastic:
    """The incremental round engine for one BatchedMastic instance."""

    def __init__(self, bm: BatchedMastic, width: int):
        assert width >= 2 and width & (width - 1) == 0
        self.bm = bm
        self.width = width
        self.bits = bm.m.vidpf.BITS

    def init_carry(self, num_reports: int, keys,
                   agg_id: int, host: bool = False) -> Carry:
        """Pre-round-0 carry: the frontier is the root key.  With
        `host`, the arrays stay numpy (the chunked runner keeps every
        chunk's carry in host memory); either way this is the single
        definition of the carry layout."""
        vid = self.bm.vidpf
        spec = self.bm.spec
        seed = np.zeros((num_reports, self.width, KEY_SIZE), np.uint8)
        seed[:, 0, :] = np.asarray(keys)
        ctrl = np.zeros((num_reports, self.width), bool)
        ctrl[:, 0] = bool(agg_id)
        carry = Carry(
            w=np.zeros((num_reports, self.bits, self.width,
                        vid.VALUE_LEN, spec.num_limbs), np.uint32),
            proof=np.zeros((num_reports, self.bits, self.width,
                            PROOF_SIZE), np.uint8),
            seed=seed, ctrl=ctrl)
        if host:
            return carry
        return Carry(*(jnp.asarray(x) for x in carry))

    # -- one aggregator's round (jittable) -------------------------

    def agg_round(self, agg_id: int, verify_key: bytes, ctx: bytes,
                  carry: Carry, rnd: IncrementalRound,
                  ext_rk: jax.Array, conv_rk: jax.Array, cws):
        """Evaluate the new level, refresh the carry, emit the eval
        proof and the (padded) truncated out share.

        Returns (carry', eval_proof (R, 32), out_share
        (R, capOut*(1+OUTPUT_LEN), n), ok (R,)) — capOut the power-of-2
        bucket of the live frontier, not the padded width.
        """
        bm = self.bm
        spec = bm.spec
        (num_reports, _bits, width, value_len, n) = carry.w.shape
        half = width // 2

        # 1. Carried depths keep their creation-time layouts (the live
        # set only shrinks, so no compaction gather — the binder
        # assembly below reads exactly the live rows by position).
        w_all = carry.w
        proof_all = carry.proof

        # 2. Gather the surviving parents from the frontier state.
        pseed = carry.seed[:, rnd.parent_idx, :]
        pctrl = carry.ctrl[:, rnd.parent_idx]
        parents = EvalState(
            seed=pseed, ctrl=pctrl,
            w=jnp.zeros((num_reports, half, value_len, n), jnp.uint32),
            proof=jnp.zeros((num_reports, half, PROOF_SIZE), _U8))

        # 3. One level step with the correction word at `level`.
        cw_slice = tuple(
            jax.lax.dynamic_index_in_dim(x, rnd.level, axis=1,
                                         keepdims=False)
            for x in (cws.seed, cws.ctrl, cws.w, cws.proof))
        (child, ok) = self._eval_step_dynamic(
            ext_rk, conv_rk, parents, cw_slice, ctx, rnd)

        # 4. Install the new depth row.
        w_all = jax.lax.dynamic_update_slice_in_dim(
            w_all, child.w[:, None], rnd.level, axis=1)
        proof_all = jax.lax.dynamic_update_slice_in_dim(
            proof_all, child.proof[:, None], rnd.level, axis=1)

        # 5. Binders + checks (byte-exact vs mastic.py:219-247).
        eval_proof = self._eval_proof(agg_id, verify_key, ctx, w_all,
                                      proof_all, rnd)

        # 6. Padded truncated out share.
        out_w = child.w[:, rnd.out_idx]
        if agg_id == 1:
            out_w = spec.neg(out_w)
        counter = out_w[..., :1, :]
        trunc = bm.truncate(out_w[..., 1:, :])
        out_share = jnp.concatenate([counter, trunc], axis=-2)
        out_share = out_share.reshape(num_reports, -1, n)

        carry = Carry(w=w_all, proof=proof_all, seed=child.seed,
                      ctrl=child.ctrl)
        return (carry, eval_proof, out_share, ok)

    def _eval_step_dynamic(self, ext_rk, conv_rk, parents: EvalState,
                           cw_slice, ctx: bytes, rnd: IncrementalRound):
        """vidpf_jax.eval_step with a runtime-length node-proof binder."""
        vid = self.bm.vidpf
        (_seed_cw, _ctrl_cw, _w_cw, proof_cw) = cw_slice
        (num_reports, num_parents) = parents.ctrl.shape

        (next_seed, ct, w, ok) = vid.level_core(ext_rk, conv_rk,
                                                parents, cw_slice)

        # Node proof with runtime-length (BITS, level, path) binder.
        proof_dst = dst(ctx, USAGE_NODE_PROOF)
        prefix = ts_prefix(proof_dst, KEY_SIZE)
        msg = jnp.concatenate([
            jnp.broadcast_to(
                jnp.asarray(np.frombuffer(prefix, np.uint8)),
                (num_reports, 2 * num_parents, len(prefix))),
            next_seed,
            jnp.broadcast_to(rnd.node_binder[None],
                             (num_reports,) + rnd.node_binder.shape),
        ], axis=-1)
        proof = turbo_shake128_dynamic(
            msg, jnp.int32(len(prefix) + KEY_SIZE) + rnd.binder_len,
            1, PROOF_SIZE)
        proof = jnp.where(ct[..., None], proof ^ proof_cw[:, None, :],
                          proof)

        child = EvalState(seed=next_seed, ctrl=ct, w=w, proof=proof)
        # Only live parent lanes count toward the rejection mask.
        lane = jnp.arange(2 * num_parents) < 2 * rnd.parent_count
        return (child, jnp.all(ok | ~lane, axis=-1))

    def _eval_proof(self, agg_id: int, verify_key: bytes, ctx: bytes,
                    w_all, proof_all, rnd: IncrementalRound):
        """The three checks over the carried tree, hashed with
        runtime-length binders (scalar semantics: mastic.py:219-247).
        Only the live rows are gathered/hashed — the index arrays are
        capacity-bucketed, so both the memory traffic and the sponge
        work scale with the live tree, not bits x width."""
        bm = self.bm
        spec = bm.spec
        (num_reports, bits, width, value_len, n) = w_all.shape
        w_flat = w_all.reshape(num_reports, bits * width, value_len, n)
        proof_flat = proof_all.reshape(num_reports, bits * width,
                                       PROOF_SIZE)

        # Payload rows: parent w minus its two children, per internal
        # node (triple gathers of exactly the live rows).
        diff = spec.sub(
            w_flat[:, rnd.payload_parent],
            spec.add(w_flat[:, rnd.payload_left],
                     w_flat[:, rnd.payload_right]))
        diff_bytes = spec.plain_to_le_bytes(diff)
        row_bytes = value_len * spec.encoded_size
        payload_binder = diff_bytes.reshape(num_reports, -1)
        payload_check = turbo_shake128_dynamic(
            _prefixed(payload_binder, ctx, USAGE_PAYLOAD_CHECK, bm.m.ID),
            _prefix_len(ctx, USAGE_PAYLOAD_CHECK, bm.m.ID)
            + rnd.payload_rows * row_bytes,
            1, PROOF_SIZE)

        onehot_binder = proof_flat[:, rnd.onehot_idx].reshape(
            num_reports, -1)
        onehot_check = turbo_shake128_dynamic(
            _prefixed(onehot_binder, ctx, USAGE_ONEHOT_CHECK, bm.m.ID),
            _prefix_len(ctx, USAGE_ONEHOT_CHECK, bm.m.ID)
            + rnd.onehot_rows * PROOF_SIZE,
            1, PROOF_SIZE)

        counter = spec.add(w_all[:, 0, 0, 0], w_all[:, 0, 1, 0])
        if agg_id == 1:
            one = np.zeros(spec.num_limbs, np.uint32)
            one[0] = 1
            counter = spec.add(counter, jnp.asarray(one))
        counter_check = spec.plain_to_le_bytes(counter)

        return turboshake_xof(
            dst_alg(ctx, USAGE_EVAL_PROOF, bm.m.ID), verify_key,
            (onehot_check, counter_check, payload_check), PROOF_SIZE,
            (num_reports,))


# -- checkpoint / resume (SURVEY.md §5; the reference's cache-across-
# -- rounds note, /root/reference/poc/vidpf.py:243-245) -------------

def carry_to_arrays(carry: Carry, prefix: str = "") -> dict:
    """A Carry as named host arrays (for np.savez-style persistence)."""
    return {
        prefix + "w": np.asarray(carry.w),
        prefix + "proof": np.asarray(carry.proof),
        prefix + "seed": np.asarray(carry.seed),
        prefix + "ctrl": np.asarray(carry.ctrl),
    }


def carry_from_arrays(arrays, prefix: str = "") -> Carry:
    """Inverse of carry_to_arrays (accepts any mapping of arrays)."""
    return Carry(
        w=jnp.asarray(np.asarray(arrays[prefix + "w"], np.uint32)),
        proof=jnp.asarray(np.asarray(arrays[prefix + "proof"],
                                     np.uint8)),
        seed=jnp.asarray(np.asarray(arrays[prefix + "seed"], np.uint8)),
        ctrl=jnp.asarray(np.asarray(arrays[prefix + "ctrl"], bool)),
    )


def _prefix_len(ctx: bytes, usage: int, alg_id: int) -> int:
    return len(ts_prefix(dst_alg(ctx, usage, alg_id), 0))


def _prefixed(binder: jax.Array, ctx: bytes, usage: int,
              alg_id: int) -> jax.Array:
    """Prepend the XofTurboShake128 empty-seed prefix so the dynamic
    sponge sees the full message."""
    prefix = ts_prefix(dst_alg(ctx, usage, alg_id), 0)
    head = jnp.broadcast_to(
        jnp.asarray(np.frombuffer(prefix, np.uint8)),
        binder.shape[:-1] + (len(prefix),))
    return jnp.concatenate([head, binder], axis=-1)
